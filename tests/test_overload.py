"""Overload protection & graceful degradation (ISSUE 15).

Layers under test:

  * AdmissionController decision order: chaos site, queue bound,
    deadline-aware rejection from EWMA estimates, memory-pressure shed,
    tenant-weighted shedding over TokenPriorityScheduler weights;
  * bounded scheduler queues (the submit-time backstop) across fcfs /
    priority / binary;
  * the typed errorCode-211 plane end to end: server rejection ->
    broker one-replica retry -> typed partial (never a raw 427) ->
    client PinotOverloadError with the parsed retryAfterMs hint;
  * RetryBudget token bucket + the retry-storm regression (flapping
    replica under multi-client load must not multiply offered load);
  * failure-detector rework: capped-exponential mark_timeout with
    jitter, lighter-weight mark_overload, hedge auto-disable;
  * brownout ladder hysteresis (unit, injectable clock) and the
    end-to-end MiniCluster SLO-burn -> climb -> recover round trip;
  * seeded chaos replay: server.admission.reject and
    broker.retry.budget decision journals byte-identical;
  * concurrent admission race: every query exactly one typed terminal
    outcome;
  * the bench --overload smoke leg (tier-1 goodput gate).
"""
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from pinot_tpu.broker.adaptive import RetryBudget
from pinot_tpu.broker.failure_detector import ConnectionFailureDetector
from pinot_tpu.health.brownout import (RUNGS, BrownoutController,
                                       _register_brownout, engaged,
                                       get_brownout, window_scale)
from pinot_tpu.health.history import MetricsHistory
from pinot_tpu.server.admission import AdmissionController
from pinot_tpu.server.scheduler import make_scheduler
from pinot_tpu.utils import errorcodes
from pinot_tpu.utils.accounting import ServerOverloadedError
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import (FailpointError, FaultSchedule,
                                        failpoints)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def _build_segment(tmp_path, name="s0", docs=500, seed=7):
    from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                                  TableConfig)
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    schema = Schema("t", [
        FieldSpec("k", DataType.INT, FieldType.DIMENSION),
        FieldSpec("v", DataType.INT, FieldType.METRIC)])
    rng = np.random.default_rng(seed)
    d = str(tmp_path / name)
    SegmentCreator(TableConfig(name="t"), schema).build(
        {"k": rng.integers(0, 100, docs).astype(np.int32),
         "v": rng.integers(0, 10, docs).astype(np.int32)}, d, name)
    return load_segment(d)


QUERY = "SELECT COUNT(*), SUM(v) FROM t OPTION(skipCache=true)"


def _mini_cluster(tmp_path, overrides=None, num_servers=2,
                  replicate=True, num_segments=2):
    from pinot_tpu.cluster.mini import MiniCluster
    cfg = PinotConfiguration(overrides=dict(overrides or {}))
    c = MiniCluster(num_servers=num_servers, config=cfg)
    c.start()
    c.add_table("t")
    for i in range(num_segments):
        seg = _build_segment(tmp_path, name=f"s{i}", seed=11 + i)
        if replicate and num_servers > 1:
            c.add_segment("t", seg, server_idx=0,
                          replicas=list(range(1, num_servers)))
        else:
            c.add_segment("t", seg, server_idx=i % num_servers)
    return c


# ---------------------------------------------------------------------------
# AdmissionController unit behavior
# ---------------------------------------------------------------------------

class TestAdmissionController:
    def test_admits_when_idle(self):
        a = AdmissionController(num_threads=2, queue_limit=4)
        assert a.admit(table="t", deadline=time.time() + 10) is None

    def test_queue_bound_rejects(self):
        a = AdmissionController(num_threads=2, queue_limit=4)
        tickets = [a.register() for _ in range(2 + 4)]
        rej = a.admit(table="t")
        assert isinstance(rej, ServerOverloadedError)
        assert "queue full" in str(rej)
        assert rej.retry_after_ms >= 10.0
        for t in tickets:
            t.release()
        assert a.admit(table="t") is None

    def test_deadline_aware_rejection_from_ewma(self):
        """A query whose remaining budget is below estimated wait+exec
        fails NOW in O(1) instead of timing out after consuming a
        worker — the heart of deadline-aware admission."""
        a = AdmissionController(num_threads=1, queue_limit=100,
                                ewma_alpha=1.0)
        # teach it: executions take ~200ms
        t = a.register()
        t.run(lambda: time.sleep(0.0))  # wait observation
        a._note_exec(0.2)
        t.release()
        # 6 queued ahead on 1 worker -> est wait ~1.2s
        tickets = [a.register() for _ in range(7)]
        rej = a.admit(table="t", deadline=time.time() + 0.3)
        assert isinstance(rej, ServerOverloadedError)
        assert "estimated wait" in str(rej)
        assert rej.retry_after_ms > 0
        # a roomy budget still admits through the same queue
        assert a.admit(table="t", deadline=time.time() + 30) is None
        for t in tickets:
            t.release()

    def test_memory_pressure_sheds(self):
        pressure = [0.0]
        a = AdmissionController(num_threads=2, queue_limit=4,
                                memory_threshold=0.9,
                                memory_pressure_fn=lambda: pressure[0])
        assert a.admit(table="t") is None
        pressure[0] = 0.97
        a._pressure_at = 0.0  # expire the memo
        rej = a.admit(table="t")
        assert isinstance(rej, ServerOverloadedError)
        assert "memory pressure" in str(rej)

    def test_tenant_weight_shed_lowest_first(self):
        """Past shed.start occupancy the weight cutoff rises toward the
        heaviest tenant: the light tenant sheds first, the heavy one
        keeps flowing until the hard queue bound."""
        weights = {"gold": 4.0, "bronze": 1.0}
        a = AdmissionController(num_threads=1, queue_limit=10,
                                shed_start=0.5,
                                tenant_weights_fn=lambda: weights)
        a._note_exec(0.01)
        tickets = [a.register() for _ in range(1 + 9)]  # 90% occupancy
        rej = a.admit(table="t", tenant="bronze")
        assert isinstance(rej, ServerOverloadedError)
        assert "shed cutoff" in str(rej)
        assert a.admit(table="t", tenant="gold") is None
        for t in tickets:
            t.release()

    def test_disabled_admits_everything(self):
        a = AdmissionController(num_threads=1, queue_limit=1,
                                enabled=False)
        tickets = [a.register() for _ in range(50)]
        assert a.admit(table="t", deadline=time.time() + 0.001) is None
        for t in tickets:
            t.release()

    def test_ticket_release_idempotent(self):
        a = AdmissionController(num_threads=1)
        t = a.register()
        t.release()
        t.release()
        assert a.snapshot()["inflight"] == 0

    def test_chaos_rejection_site(self):
        a = AdmissionController(num_threads=2, queue_limit=4)
        with failpoints.armed(
                "server.admission.reject",
                error=ServerOverloadedError("chaos", retry_after_ms=77)):
            rej = a.admit(table="t")
        assert isinstance(rej, ServerOverloadedError)
        assert rej.retry_after_ms == 77.0
        assert a.admit(table="t") is None


# ---------------------------------------------------------------------------
# bounded scheduler queues (the backstop)
# ---------------------------------------------------------------------------

class TestBoundedSchedulers:
    @pytest.mark.parametrize("kind", ["fcfs", "priority", "binary"])
    def test_full_queue_raises_typed(self, kind):
        gate = threading.Event()
        sched = make_scheduler(kind, num_threads=1)
        sched.start()
        try:
            sched.set_queue_limit(2)
            started = threading.Event()

            def first():
                started.set()
                gate.wait(10)
                return b""

            futs = [sched.submit(first)]
            assert started.wait(5), "worker never picked up"
            # worker occupied: exactly `limit` submissions may queue,
            # the next must be REFUSED typed, not silently queued
            futs += [sched.submit(lambda: gate.wait(10))
                     for _ in range(2)]
            with pytest.raises(ServerOverloadedError) as ei:
                sched.submit(lambda: gate.wait(10))
            assert ei.value.ERROR_CODE == errorcodes.SERVER_OVERLOADED
            gate.set()
            for f in futs:
                f.result(timeout=5)
            # drained queue admits again
            sched.submit(lambda: b"").result(timeout=5)
        finally:
            gate.set()
            sched.stop()

    def test_unbounded_by_default(self):
        sched = make_scheduler("fcfs", num_threads=1)
        sched.start()
        try:
            gate = threading.Event()
            futs = [sched.submit(gate.wait) for _ in range(64)]
            gate.set()
            for f in futs:
                f.result(timeout=5)
        finally:
            sched.stop()

    def test_tenant_weights_exposed(self):
        sched = make_scheduler("priority", num_threads=1)
        sched.set_tenant_weight("gold", 4.0)
        assert sched.tenant_weights() == {"gold": 4.0}
        assert sched.tenant_weight("gold") == 4.0
        assert sched.tenant_weight("unknown") == 1.0


# ---------------------------------------------------------------------------
# RetryBudget
# ---------------------------------------------------------------------------

class TestRetryBudget:
    def test_min_tokens_then_exhaustion(self):
        b = RetryBudget(ratio=0.0, min_tokens=2.0, cap=5.0)
        assert b.try_withdraw("t")
        assert b.try_withdraw("t")
        assert not b.try_withdraw("t")

    def test_successes_refill_up_to_cap(self):
        b = RetryBudget(ratio=0.5, min_tokens=1.0, cap=2.0)
        assert b.try_withdraw("t")
        assert not b.try_withdraw("t")
        for _ in range(10):
            b.deposit("t")
        assert b.tokens("t") == 2.0  # capped
        assert b.try_withdraw("t")
        assert b.try_withdraw("t")
        assert not b.try_withdraw("t")

    def test_tables_isolated(self):
        b = RetryBudget(ratio=0.0, min_tokens=1.0)
        assert b.try_withdraw("a")
        assert not b.try_withdraw("a")
        assert b.try_withdraw("b")

    def test_disabled_always_grants(self):
        b = RetryBudget(ratio=0.0, min_tokens=0.0, enabled=False)
        for _ in range(100):
            assert b.try_withdraw("t")


# ---------------------------------------------------------------------------
# failure detector: timeout backoff + overload marks
# ---------------------------------------------------------------------------

class TestFailureDetectorBackoff:
    def test_timeout_backoff_grows_and_caps(self):
        d = ConnectionFailureDetector(base_backoff_s=1.0,
                                      max_backoff_s=8.0, jitter_seed=3)
        spans = []
        for _ in range(6):
            before = time.time()
            d.mark_timeout("s")
            with d._lock:
                spans.append(d._entries["s"].retry_at - before)
        # capped exponential: grows (jitter in [0.5, 1.0] cannot mask a
        # doubling) and never exceeds the ceiling
        assert spans[2] > spans[0]
        assert all(s <= 8.0 + 0.01 for s in spans)
        assert spans[-1] >= 2.0  # well past the old flat single base

    def test_timeout_jitter_is_seeded(self):
        a = ConnectionFailureDetector(jitter_seed=42)
        b = ConnectionFailureDetector(jitter_seed=42)
        now = time.time()
        a.mark_timeout("s")
        b.mark_timeout("s")
        with a._lock:
            ra = a._entries["s"].retry_at - now
        with b._lock:
            rb = b._entries["s"].retry_at - now
        assert abs(ra - rb) < 0.05

    def test_overload_lighter_than_timeout(self):
        """The same number of overload marks must exile a server for
        LESS time than timeout marks — saturated is not dead."""
        t = ConnectionFailureDetector(base_backoff_s=1.0,
                                      max_backoff_s=60.0, jitter_seed=1)
        o = ConnectionFailureDetector(base_backoff_s=1.0,
                                      max_backoff_s=60.0, jitter_seed=1)
        now = time.time()
        for _ in range(6):
            t.mark_timeout("s")
            o.mark_overload("s")
        with t._lock:
            t_span = t._entries["s"].retry_at - now
        with o._lock:
            o_span = o._entries["s"].retry_at - now
        assert o_span < t_span
        assert o_span <= 60.0 / 4.0 + 0.01  # quarter ceiling

    def test_overload_horizon_and_success_clears(self):
        d = ConnectionFailureDetector(base_backoff_s=0.2, jitter_seed=2)
        assert not d.any_overloaded()
        d.mark_overload("s", retry_after_s=5.0)
        assert d.any_overloaded()
        assert d.overloaded_servers() == {"s"}
        d.mark_success("s")
        assert not d.any_overloaded()
        assert d.is_healthy("s")

    def test_retry_after_hint_respected(self):
        d = ConnectionFailureDetector(base_backoff_s=0.01,
                                      max_backoff_s=60.0, jitter_seed=4)
        now = time.time()
        d.mark_overload("s", retry_after_s=3.0)
        with d._lock:
            span = d._entries["s"].overload_until - now
        assert 2.9 <= span <= 60.0 / 4.0 + 0.01


# ---------------------------------------------------------------------------
# the typed 211 plane end to end
# ---------------------------------------------------------------------------

class TestOverloadEndToEnd:
    def test_forced_rejection_surfaces_typed_partial(self, tmp_path):
        """Both replicas rejecting: the broker retries once, then
        surfaces a typed 211 (retryAfterMs intact) — never a 427."""
        c = _mini_cluster(tmp_path)
        try:
            assert not c.query(QUERY).exceptions
            with failpoints.armed(
                    "server.admission.reject",
                    error=ServerOverloadedError("drill",
                                                retry_after_ms=42)):
                resp = c.query(QUERY)
            assert resp.partial_result
            codes = {e["errorCode"] for e in resp.exceptions}
            assert codes == {errorcodes.SERVER_OVERLOADED}
            assert any("retryAfterMs=42" in e["message"]
                       for e in resp.exceptions)
        finally:
            c.stop()

    def test_one_replica_overloaded_other_absorbs(self, tmp_path):
        """A single saturated replica: the overload retries onto the
        twin and the query answers CLEAN — overload protection must be
        invisible while capacity exists elsewhere."""
        c = _mini_cluster(tmp_path)
        try:
            baseline = c.query(QUERY)
            assert not baseline.exceptions
            with failpoints.armed(
                    "server.admission.reject",
                    error=ServerOverloadedError("saturated",
                                                retry_after_ms=30),
                    where={"table": "t_OFFLINE"}, times=1):
                resp = c.query(QUERY)
            assert not resp.exceptions, resp.exceptions
            assert resp.rows == baseline.rows
            # the rejecting server was cooled at overload weight: its
            # overload horizon is open, so hedging is auto-disabled
            assert c.broker.failure_detector.any_overloaded()
            assert c.broker._hedge_delay_s() is None
        finally:
            c.stop()

    def test_budget_exhaustion_stops_the_retry(self, tmp_path):
        """broker.retry.budget armed to exhaust: the overload surfaces
        typed WITHOUT a second server attempt — rejections cannot
        amplify."""
        c = _mini_cluster(tmp_path)
        try:
            assert not c.query(QUERY).exceptions
            before = self._server_queries(c)
            with failpoints.armed(
                    "server.admission.reject",
                    error=ServerOverloadedError("drill",
                                                retry_after_ms=10),
                    times=1), \
                 failpoints.armed("broker.retry.budget",
                                  error=FailpointError("budget dry")):
                resp = c.query(QUERY)
            codes = {e["errorCode"] for e in resp.exceptions}
            assert codes == {errorcodes.SERVER_OVERLOADED}
            assert any("retry budget exhausted" in e["message"]
                       for e in resp.exceptions)
            # exactly ONE server attempt (the rejected one — rejections
            # don't execute, so the counter must not move at all)
            assert self._server_queries(c) == before
        finally:
            c.stop()

    @staticmethod
    def _server_queries(c) -> float:
        from pinot_tpu.utils.metrics import get_registry
        counters = get_registry("server").sample()["counters"]
        return sum(v for k, v in counters.items()
                   if k == "queries" or k.startswith("queries{"))

    def test_client_maps_overload_error(self, tmp_path):
        from pinot_tpu.client.connection import (PinotOverloadError,
                                                 connect)
        c = _mini_cluster(tmp_path)
        try:
            from pinot_tpu.broker.http_api import BrokerHttpServer
            http = BrokerHttpServer(c.broker)
            http.start()
            try:
                conn = connect(f"127.0.0.1:{http.port}")
                with failpoints.armed(
                        "server.admission.reject",
                        error=ServerOverloadedError(
                            "drill", retry_after_ms=55)):
                    with pytest.raises(PinotOverloadError) as ei:
                        conn.execute(QUERY)
                assert ei.value.retry_after_ms == 55.0
                assert ei.value.result_set is not None
                assert ei.value.result_set.partial_result
            finally:
                http.stop()
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# seeded chaos replay (byte-identical journals)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestOverloadChaosReplay:
    def _run_schedule(self, tmp_path, sub, seed):
        sched = FaultSchedule([
            ("server.admission.reject",
             {"error": ServerOverloadedError("chaos", retry_after_ms=20),
              "probability": 0.5, "seed": seed}),
            ("broker.retry.budget",
             {"error": FailpointError("chaos budget"),
              "probability": 0.5, "seed": seed + 1}),
        ])
        c = _mini_cluster(tmp_path / sub)
        sched.arm()
        outcomes = []
        try:
            for _ in range(24):
                resp = c.query(QUERY)
                outcomes.append(tuple(sorted(
                    e["errorCode"] for e in resp.exceptions)))
        finally:
            decisions = sched.decisions()
            sched.disarm()
            c.stop()
        return decisions, outcomes

    def test_same_seed_replays_byte_identical(self, tmp_path):
        d1, o1 = self._run_schedule(tmp_path, "a", seed=97)
        d2, o2 = self._run_schedule(tmp_path, "b", seed=97)
        assert d1 == d2          # per-site decision journals, exactly
        assert o1 == o2          # and the query outcomes they drove
        assert any(fired for log in d1 for fired, _ in log), \
            "schedule never fired — replay proves nothing"

    def test_different_seed_differs(self, tmp_path):
        d1, _ = self._run_schedule(tmp_path, "a", seed=97)
        d2, _ = self._run_schedule(tmp_path, "b", seed=1234)
        assert d1 != d2


# ---------------------------------------------------------------------------
# retry-storm regression + concurrent admission race
# ---------------------------------------------------------------------------

class TestRetryStormRegression:
    def test_flapping_replica_bounded_retry_ratio(self, tmp_path):
        """One replica flapping (50% connection drops) under 8-client
        load: server-side attempts must stay within the budgeted
        multiple of offered queries — no storm."""
        from pinot_tpu.utils.metrics import get_registry
        c = _mini_cluster(tmp_path, overrides={
            "pinot.broker.retry.budget.ratio": 0.2,
            "pinot.broker.retry.budget.min": 3.0})
        try:
            assert not c.query(QUERY).exceptions
            b0 = self._counter(get_registry("broker"), "broker_queries")
            r0 = self._counter(get_registry("broker"),
                               "broker_retries_issued")
            n_per_client, clients = 12, 8
            with failpoints.armed("broker.scatter.before",
                                  error=ConnectionError("flap"),
                                  probability=0.5, seed=5,
                                  where={"server": "server_1"}):
                def loop():
                    for _ in range(n_per_client):
                        c.query(QUERY)  # partials allowed; hangs not
                with ThreadPoolExecutor(max_workers=clients) as pool:
                    for f in [pool.submit(loop) for _ in range(clients)]:
                        f.result(timeout=60)
            queries = self._counter(get_registry("broker"),
                                    "broker_queries") - b0
            retries = self._counter(get_registry("broker"),
                                    "broker_retries_issued") - r0
            assert queries == n_per_client * clients + 1 or \
                queries >= n_per_client * clients
            # the bound: ratio * queries + the min floor + slack for the
            # deposits earned by clean responses mid-run
            assert retries <= 0.2 * queries + 3.0 + 2.0, \
                (retries, queries)
        finally:
            c.stop()

    @staticmethod
    def _counter(reg, family) -> float:
        counters = reg.sample()["counters"]
        return sum(v for k, v in counters.items()
                   if k == family or k.startswith(family + "{"))


class TestConcurrentAdmissionRace:
    def test_every_query_one_typed_terminal_outcome(self, tmp_path):
        """N clients racing a tiny queue: every query returns exactly
        one outcome — clean rows, or a typed 211/250 partial. No hangs,
        no untyped raises, no silent drops."""
        c = _mini_cluster(tmp_path, overrides={
            "pinot.server.query.num.threads": 1,
            "pinot.server.admission.queue.limit": 2,
            "pinot.broker.timeout.ms": 4000})
        try:
            assert not c.query(QUERY).exceptions
            outcomes = []
            lock = threading.Lock()
            with failpoints.armed("server.execute.before", delay=0.03):
                def loop():
                    for _ in range(10):
                        resp = c.query(QUERY)
                        codes = tuple(sorted(
                            e["errorCode"] for e in resp.exceptions))
                        with lock:
                            outcomes.append((codes, len(resp.rows)))
                with ThreadPoolExecutor(max_workers=12) as pool:
                    for f in [pool.submit(loop) for _ in range(12)]:
                        f.result(timeout=120)
            assert len(outcomes) == 120
            allowed = {errorcodes.SERVER_OVERLOADED,
                       errorcodes.EXECUTION_TIMEOUT}
            for codes, rows in outcomes:
                if codes:
                    assert set(codes) <= allowed, codes
                else:
                    assert rows == 1
            # the race actually exercised the rejection path
            assert any(errorcodes.SERVER_OVERLOADED in codes
                       for codes, _ in outcomes), \
                "queue never overflowed — race not exercised"
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# brownout ladder
# ---------------------------------------------------------------------------

def _brownout(history=None, watchdog=None, **knobs):
    cfg = PinotConfiguration(overrides={
        "pinot.brownout.up.seconds": 1.0,
        "pinot.brownout.down.seconds": 2.0,
        "pinot.brownout.shed.rate.threshold": 0.1,
        "pinot.slo.window.short.seconds": 10.0,
        **knobs})
    # NOT `history or ...`: an EMPTY MetricsHistory is falsy (__len__)
    return BrownoutController(
        "testrole",
        history if history is not None else MetricsHistory(64),
        config=cfg, watchdog=watchdog)


class _FakeWatchdog:
    def __init__(self):
        self.is_breached = False

    def breached(self):
        return self.is_breached


class TestBrownoutHysteresis:
    def test_climbs_only_after_sustained_signal(self):
        dog = _FakeWatchdog()
        b = _brownout(watchdog=dog)
        t0 = 1000.0
        dog.is_breached = True
        assert b.evaluate(now=t0) == 0          # signal starts
        assert b.evaluate(now=t0 + 0.5) == 0    # not sustained yet
        assert b.evaluate(now=t0 + 1.1) == 1    # one rung after up_s
        # the next rung needs ANOTHER full sustain period
        assert b.evaluate(now=t0 + 1.5) == 1
        assert b.evaluate(now=t0 + 2.2) == 2

    def test_blip_does_not_climb(self):
        dog = _FakeWatchdog()
        b = _brownout(watchdog=dog)
        t0 = 1000.0
        dog.is_breached = True
        b.evaluate(now=t0)
        dog.is_breached = False
        b.evaluate(now=t0 + 0.5)                # signal cleared
        dog.is_breached = True
        b.evaluate(now=t0 + 0.9)
        assert b.evaluate(now=t0 + 1.5) == 0    # clock restarted at 0.9

    def test_descends_only_after_sustained_clear(self):
        dog = _FakeWatchdog()
        b = _brownout(watchdog=dog)
        t0 = 1000.0
        dog.is_breached = True
        b.evaluate(now=t0)
        b.evaluate(now=t0 + 1.1)
        assert b.level() == 1
        dog.is_breached = False
        assert b.evaluate(now=t0 + 2.0) == 1    # clear starts
        assert b.evaluate(now=t0 + 3.0) == 1    # not sustained
        assert b.evaluate(now=t0 + 4.1) == 0    # down after down_s

    def test_shed_rate_hysteresis_band_holds_rung(self):
        """Between exit (half the entry threshold) and entry thresholds
        the ladder HOLDS: no climb, no descent — the anti-flap band.
        The 10s shed-rate window slides, so each phase feeds its own
        sample pair and evaluates with only that pair in window."""
        hist = MetricsHistory(64)

        def feed(shed, queries, ts):
            hist.append({"ts": ts, "counters": {
                "server_admission_rejected": shed,
                "queries": queries}, "gauges": {}, "timers": {}})

        b = _brownout(history=hist)
        # phase A — rate 0.2 over the window: signal, climb after up_s
        feed(0, 0, 1000.0)
        feed(20, 100, 1005.0)
        b.evaluate(now=1005.0)
        assert b.evaluate(now=1006.1) == 1
        # phase B — rate 7/100 = 0.07: below entry 0.1, above exit 0.05
        feed(27, 200, 1016.0)
        feed(34, 300, 1018.0)
        for now in (1018.0, 1019.5, 1021.0, 1024.0):
            assert b.evaluate(now=now) == 1
        # phase C — rate 0: clear, descends only after down_s
        feed(34, 400, 1029.0)
        feed(34, 500, 1031.0)
        assert b.evaluate(now=1031.0) == 1
        assert b.evaluate(now=1033.2) == 0

    def test_rung_engagement_order_and_window_scale(self):
        dog = _FakeWatchdog()
        b = _brownout(watchdog=dog)
        _register_brownout("testrole", b)
        try:
            dog.is_breached = True
            t0 = 2000.0
            b.evaluate(now=t0)
            for i, rung in enumerate(RUNGS):
                b.evaluate(now=t0 + (i + 1) * 1.1)
                assert b.engaged(rung), (i, rung)
                assert all(b.engaged(r) for r in RUNGS[:i + 1])
                assert not any(b.engaged(r) for r in RUNGS[i + 1:])
            assert engaged("testrole", "shed_secondary")
            assert window_scale("testrole") == 0.25
            assert window_scale("some_other_role") == 1.0
            payload = b.payload()
            assert payload["level"] == 4 and not payload["ok"]
            assert payload["engaged"] == list(RUNGS)
        finally:
            _register_brownout("testrole", None)
        assert not engaged("testrole", "hedge_off")

    def test_disabled_never_moves(self):
        dog = _FakeWatchdog()
        b = _brownout(watchdog=dog, **{"pinot.brownout.enabled": False})
        dog.is_breached = True
        for dt in (0.0, 2.0, 10.0):
            assert b.evaluate(now=1000.0 + dt) == 0


class TestBrownoutActuation:
    def test_stale_cache_serving_flagged(self):
        from pinot_tpu.cache.core import LruTtlCache
        clock = [0.0]
        cache = LruTtlCache(1 << 20, ttl_seconds=1.0,
                            clock=lambda: clock[0],
                            stale_grace_seconds=10.0)
        cache.put("k", b"payload")
        assert cache.get("k") == b"payload"
        clock[0] = 2.0            # past TTL, inside grace
        assert cache.get("k") is None          # normal read: miss
        assert cache.get_stale("k") == b"payload"
        clock[0] = 12.0           # past TTL + grace
        assert cache.get_stale("k") is None
        assert len(cache) == 0    # reclaimed

    def test_stale_grace_zero_restores_delete_on_expiry(self):
        from pinot_tpu.cache.core import LruTtlCache
        clock = [0.0]
        cache = LruTtlCache(1 << 20, ttl_seconds=1.0,
                            clock=lambda: clock[0])
        cache.put("k", b"payload")
        clock[0] = 2.0
        assert cache.get("k") is None
        assert len(cache) == 0
        assert cache.get_stale("k") is None

    def test_broker_result_cache_stale_path(self):
        from pinot_tpu.cache.broker_cache import BrokerResultCache
        from pinot_tpu.query.reduce import BrokerResponse, ResultTable
        cache = BrokerResultCache(ttl_seconds=0.05,
                                  stale_grace_seconds=60.0)
        resp = BrokerResponse(result_table=ResultTable(
            ["c"], ["LONG"], [(1,)]))
        resp.num_servers_queried = resp.num_servers_responded = 1
        assert cache.put("fp", "t", "e1", resp)
        time.sleep(0.08)
        assert cache.get("fp", "t", "e1") is None
        stale = cache.get("fp", "t", "e1", allow_stale=True)
        assert stale is not None and stale.stale_result
        assert stale.rows == [(1,)]
        # staleResult surfaces in the client payload
        assert stale.to_dict()["staleResult"] is True

    def test_secondary_workload_shed_at_full_brownout(self):
        dog = _FakeWatchdog()
        hist = MetricsHistory(8)
        cfg = PinotConfiguration(overrides={
            "pinot.brownout.up.seconds": 0.0,
            "pinot.brownout.down.seconds": 10.0})
        b = BrownoutController("server", hist, config=cfg, watchdog=dog)
        _register_brownout("server", b)
        try:
            dog.is_breached = True
            for i in range(len(RUNGS)):
                b.evaluate(now=3000.0 + i)
            assert b.level() == len(RUNGS)
            a = AdmissionController(num_threads=2, queue_limit=4)
            rej = a.admit(table="t", workload="secondary")
            assert isinstance(rej, ServerOverloadedError)
            assert "secondary workloads shed" in str(rej)
            assert a.admit(table="t", workload="primary") is None
        finally:
            _register_brownout("server", None)

    def test_hedge_off_rung_disables_broker_hedging(self, tmp_path):
        dog = _FakeWatchdog()
        cfg = PinotConfiguration(overrides={
            "pinot.brownout.up.seconds": 0.0})
        b = BrownoutController("broker", MetricsHistory(8), config=cfg,
                               watchdog=dog)
        c = _mini_cluster(tmp_path, overrides={
            "pinot.broker.hedge.enabled": True})
        try:
            assert c.broker._hedge_delay_s() is not None
            _register_brownout("broker", b)
            dog.is_breached = True
            b.evaluate(now=4000.0)
            assert b.engaged("hedge_off")
            assert c.broker._hedge_delay_s() is None
        finally:
            _register_brownout("broker", None)
            c.stop()


@pytest.mark.chaos
class TestBrownoutEndToEnd:
    def test_slo_burn_drives_ladder_up_and_down(self, tmp_path):
        """The full observe->act loop on a live MiniCluster: a forced
        error burn breaches the SLO watchdog, the sampler-hooked
        brownout controller climbs; the burn stops, the windows clear,
        the ladder walks back down. Uses the REAL start_sampling wiring
        (watchdog + brownout hooks, per-role registration)."""
        from pinot_tpu.health.history import (get_history, start_sampling,
                                              stop_sampling)
        from pinot_tpu.health.rollup import role_health_summary
        from pinot_tpu.health.slo import get_watchdog
        overrides = {
            "pinot.slo.error.rate": 0.01,
            "pinot.slo.window.short.seconds": 1.0,
            "pinot.slo.window.long.seconds": 2.0,
            "pinot.slo.burn.threshold": 1.0,
            "pinot.metrics.history.interval.ms": 50.0,
            "pinot.brownout.up.seconds": 0.3,
            "pinot.brownout.down.seconds": 0.6,
        }
        cfg = PinotConfiguration(overrides=overrides)
        c = _mini_cluster(tmp_path, overrides=overrides)
        get_history("broker").clear()
        sampler = start_sampling("broker", cfg)
        assert sampler is not None
        try:
            ctrl = get_brownout("broker")
            assert ctrl is not None and get_watchdog("broker") is not None
            # -- burn: every query errors (way past the 1% target) -----
            deadline = time.time() + 12.0
            with failpoints.armed("server.execute.before",
                                  error=RuntimeError("burn")):
                while time.time() < deadline and ctrl.level() == 0:
                    resp = c.query(QUERY)
                    assert resp.exceptions
                    time.sleep(0.01)
            assert ctrl.level() >= 1, "burn never climbed the ladder"
            payload = role_health_summary("broker")
            assert payload["subsystems"]["brownout"]["level"] >= 1
            assert not payload["subsystems"]["brownout"]["ok"]
            assert "brownout" in payload["degraded"] or \
                payload["verdict"] == "degraded"
            # -- recover: clean traffic until the windows forget -------
            deadline = time.time() + 25.0
            while time.time() < deadline and ctrl.level() > 0:
                resp = c.query(QUERY)
                assert not resp.exceptions
                time.sleep(0.01)
            assert ctrl.level() == 0, "ladder never walked back down"
            assert role_health_summary(
                "broker")["subsystems"]["brownout"]["ok"]
        finally:
            stop_sampling("broker")
            c.stop()
        assert get_brownout("broker") is None


# ---------------------------------------------------------------------------
# tier-1 smoke of the acceptance driver
# ---------------------------------------------------------------------------

class TestOverloadBenchSmoke:
    def test_overload_bench_smoke(self, tmp_path):
        """The --overload acceptance scenario at smoke scale: protected
        goodput holds under 4x offered load, the unprotected A/B leg
        degrades, zero hung queries, CI-tolerant overhead bound."""
        import bench
        out = str(tmp_path / "BENCH_overload_smoke.json")
        bench.overload_main(smoke=True, out_path=out)
        import json
        data = json.loads(open(out).read())
        assert data["smoke"] is True
        assert data["hung_queries_total"] == 0
        assert data["admission_rejects"] > 0
