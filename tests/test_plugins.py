"""Plugin registry + loader: the PluginManager/ServiceLoader analog.

Ref: pinot-spi plugin/PluginManager.java:52, segment-spi
index/IndexPlugin.java — VERDICT r4 missing #5 / next-round task 9:
index types, codecs, streams, and input formats resolve through one
registration seam; built-ins (CLP, TCP stream) prove it.
"""
import numpy as np
import pytest

from pinot_tpu.utils import plugins


class TestRegistry:
    def test_register_get_available(self):
        plugins.register("testkind", "Foo", object)
        assert plugins.get("testkind", "foo") is object  # case-insensitive
        assert "foo" in plugins.available("testkind")
        with pytest.raises(KeyError):
            plugins.get("testkind", "missing")

    def test_builtins_registered_through_seam(self):
        plugins.load_builtin_plugins()
        import pinot_tpu.ingest.batch  # noqa: F401 — registers formats
        import pinot_tpu.ingest.memory_stream  # noqa: F401
        import pinot_tpu.segment.fs  # noqa: F401
        assert plugins.is_registered("stream", "tcp")
        assert plugins.is_registered("stream", "inmemory")
        assert plugins.is_registered("fs", "file")
        assert plugins.is_registered("index", "clp_forward")
        for fmt in ("csv", "json", "parquet", "avro"):
            assert plugins.is_registered("input_format", fmt)


class TestDirectoryLoading:
    def test_load_plugin_dir_registers_custom_format(self, tmp_path):
        pdir = tmp_path / "plugins"
        pdir.mkdir()
        (pdir / "tsv_format.py").write_text(
            "from pinot_tpu.utils import plugins\n"
            "def read_tsv(path):\n"
            "    with open(path) as f:\n"
            "        header = f.readline().rstrip('\\n').split('\\t')\n"
            "        for line in f:\n"
            "            yield dict(zip(header,\n"
            "                           line.rstrip('\\n').split('\\t')))\n"
            "plugins.register('input_format', 'tsv', read_tsv)\n")
        loaded = plugins.load_plugin_dir(str(pdir))
        assert loaded == ["pinot_tpu_plugin_tsv_format"]
        # the ingestion path now reads the plugin's format
        from pinot_tpu.ingest.batch import read_records
        data = tmp_path / "rows.tsv"
        data.write_text("a\tb\n1\tx\n2\ty\n")
        rows = list(read_records(str(data), fmt="tsv"))
        assert rows == [{"a": "1", "b": "x"}, {"a": "2", "b": "y"}]

    def test_bad_plugin_does_not_kill_loading(self, tmp_path):
        pdir = tmp_path / "plugins"
        pdir.mkdir()
        (pdir / "broken.py").write_text("raise RuntimeError('boom')\n")
        (pdir / "ok.py").write_text(
            "from pinot_tpu.utils import plugins\n"
            "plugins.register('testkind2', 'ok', 42)\n")
        loaded = plugins.load_plugin_dir(str(pdir))
        assert loaded == ["pinot_tpu_plugin_ok"]
        assert plugins.get("testkind2", "ok") == 42


class TestClpThroughSeam:
    def test_clp_column_builds_and_reads_via_registry(self, tmp_path):
        from pinot_tpu.models import (DataType, FieldSpec, FieldType,
                                      Schema, TableConfig)
        from pinot_tpu.segment.creator import SegmentCreator
        from pinot_tpu.segment.loader import load_segment
        schema = Schema("logs", [
            FieldSpec("msg", DataType.STRING, FieldType.DIMENSION)])
        tc = TableConfig(name="logs")
        tc.indexing.clp_columns = ["msg"]
        msgs = [f"connect from 10.0.0.{i} port {4000 + i}" for i in range(50)]
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build({"msg": msgs}, out, "s0")
        seg = load_segment(out)
        got = [str(v) for v in seg.data_source("msg").values()]
        assert got == msgs
