"""Realtime ingestion: stream -> mutable segment -> queryable -> sealed
(the LLCRealtimeClusterIntegrationTest analog, SURVEY.md §3.3)."""
import time

import numpy as np
import pytest

from pinot_tpu.ingest import (
    InMemoryStream, LongMsgOffset, MutableSegment, StreamConfig,
    TransformPipeline)
from pinot_tpu.ingest.realtime_manager import (
    IngestionDelayTracker, RealtimeSegmentDataManager)
from pinot_tpu.models import (DataType, FieldSpec, FieldType, IngestionConfig,
                              Schema, TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.server.data_manager import TableDataManager


def make_schema():
    return Schema("rt", [
        FieldSpec("id", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("name", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("score", DataType.DOUBLE, FieldType.METRIC),
    ])


def make_config():
    return TableConfig("rt", TableType.REALTIME)


class TestMutableSegment:
    def test_index_and_query(self):
        seg = MutableSegment("rt__0__0__1", make_config(), make_schema())
        for i in range(100):
            seg.index({"id": i, "name": f"n{i % 5}", "score": float(i)})
        assert seg.num_docs == 100
        ex = QueryExecutor([seg], use_tpu=False)
        r = ex.execute("SELECT COUNT(*), SUM(score) FROM rt WHERE id < 50")
        assert r.rows[0][0] == 50
        assert r.rows[0][1] == pytest.approx(sum(range(50)))
        r = ex.execute("SELECT name, COUNT(*) FROM rt GROUP BY name "
                       "ORDER BY name LIMIT 10")
        assert len(r.rows) == 5
        assert all(c == 20 for _, c in r.rows)

    def test_null_handling(self):
        seg = MutableSegment("rt__0__0__1", make_config(), make_schema())
        seg.index({"id": 1, "name": None, "score": None})
        ds = seg.data_source("score")
        assert ds.null_value_vector is not None
        assert ds.values()[0] == 0.0  # metric default

    def test_snapshot_isolation(self):
        seg = MutableSegment("rt__0__0__1", make_config(), make_schema())
        for i in range(10):
            seg.index({"id": i, "name": "x", "score": 1.0})
        ds = seg.data_source("id")
        seg.index({"id": 10, "name": "x", "score": 1.0})
        assert len(ds.values()) == 10  # bound at snapshot time


class TestTransformPipeline:
    def test_filter_and_transform(self):
        tc = make_config()
        tc.ingestion = IngestionConfig(
            transform_configs=[
                {"columnName": "score", "transformFunction": "id * 2"}],
            filter_function="id >= 100")
        p = TransformPipeline(tc, make_schema())
        assert p.transform({"id": 100, "name": "x"}) is None  # filtered out
        out = p.transform({"id": 3, "name": "x"})
        assert out["score"] == 6.0
        assert isinstance(out["score"], float)

    def test_type_coercion_and_defaults(self):
        p = TransformPipeline(make_config(), make_schema())
        out = p.transform({"id": "42", "name": 7})
        assert out["id"] == 42
        assert out["name"] == "7"
        assert out["score"] is None  # nulls survive to creator default fill


class TestRealtimeLifecycle:
    def test_consume_seal_rotate(self, tmp_path):
        topic = InMemoryStream("rt_topic", num_partitions=1)
        try:
            tdm = TableDataManager("rt_REALTIME")
            commits = []
            sc = StreamConfig(stream_type="inmemory", topic="rt_topic",
                              flush_threshold_rows=100)
            mgr = RealtimeSegmentDataManager(
                make_config(), make_schema(), sc, 0, tdm, str(tmp_path),
                on_commit=lambda name, off: commits.append((name, off)))
            # publish 250 rows -> expect 2 sealed segments + 50 consuming
            for i in range(250):
                topic.publish({"id": i, "name": f"n{i % 3}", "score": i * 1.0})
            mgr.start()
            deadline = time.time() + 30
            while time.time() < deadline:
                segs = [s.segment for s in tdm.acquire_segments()]
                total = sum(s.num_docs for s in segs)
                TableDataManager.release_all(
                    [s for s in tdm.acquire_segments()])  # balance below
                if total >= 250 and len(commits) >= 2:
                    break
                time.sleep(0.1)
            mgr.stop()
            assert len(commits) == 2, commits
            # offsets checkpointed monotonically
            assert commits[0][1] == LongMsgOffset(100)
            assert commits[1][1] == LongMsgOffset(200)
            # all 250 rows queryable across sealed + consuming segments
            sdms = tdm.acquire_segments()
            try:
                ex = QueryExecutor([s.segment for s in sdms], use_tpu=False)
                r = ex.execute("SELECT COUNT(*), SUM(id) FROM rt LIMIT 10")
                assert r.rows[0][0] == 250
                assert r.rows[0][1] == pytest.approx(sum(range(250)))
            finally:
                TableDataManager.release_all(sdms)
        finally:
            InMemoryStream.delete("rt_topic")

    def test_restart_resumes_from_checkpoint(self, tmp_path):
        topic = InMemoryStream("rt_topic2", num_partitions=1)
        try:
            tdm = TableDataManager("rt_REALTIME")
            sc = StreamConfig(stream_type="inmemory", topic="rt_topic2",
                              flush_threshold_rows=1000)
            for i in range(100):
                topic.publish({"id": i, "name": "a", "score": 1.0})
            # simulate a committed checkpoint at offset 40: restart consumer
            mgr = RealtimeSegmentDataManager(
                make_config(), make_schema(), sc, 0, tdm, str(tmp_path),
                start_offset=LongMsgOffset(40))
            mgr.start()
            deadline = time.time() + 15
            while time.time() < deadline and mgr.mutable.num_docs < 60:
                time.sleep(0.05)
            mgr.stop()
            assert mgr.mutable.num_docs == 60  # rows 40..99 only
        finally:
            InMemoryStream.delete("rt_topic2")


class TestIngestionDelay:
    def test_delay_tracking(self):
        t = IngestionDelayTracker()
        now_ms = int(time.time() * 1000)
        t.record(0, now_ms - 5000)
        assert t.delay_ms(0) == pytest.approx(5000, abs=2000)
        assert t.delay_ms(1) is None


@pytest.mark.chaos
class TestIngestChaos:
    """ingest.realtime.consume failpoint: the consumer loop must absorb a
    failing upstream (back off, resume, lose nothing)."""

    def test_consumer_survives_fetch_chaos(self, tmp_path):
        from pinot_tpu.utils.failpoints import FailpointError, failpoints
        topic = InMemoryStream("rt_chaos", num_partitions=1)
        failpoints.arm("ingest.realtime.consume",
                       error=FailpointError("upstream down"), times=2)
        try:
            tdm = TableDataManager("rt_REALTIME")
            sc = StreamConfig(stream_type="inmemory", topic="rt_chaos",
                              flush_threshold_rows=1000)
            for i in range(50):
                topic.publish({"id": i, "name": "a", "score": 1.0})
            mgr = RealtimeSegmentDataManager(
                make_config(), make_schema(), sc, 0, tdm, str(tmp_path))
            mgr.start()
            deadline = time.time() + 20
            while time.time() < deadline and mgr.mutable.num_docs < 50:
                time.sleep(0.05)
            mgr.stop()
            # both chaos hits consumed by backoff, zero rows lost
            assert mgr.mutable.num_docs == 50
            assert failpoints.count("ingest.realtime.consume") == 2
        finally:
            failpoints.disarm("ingest.realtime.consume")
            InMemoryStream.delete("rt_chaos")
