"""Self-healing cluster maintenance (ISSUE 18): the journaled
minimal-disruption move engine, automatic failure repair, and the
closed retention loop.

Covers:
  * MoveJournal discipline (last snapshot wins, torn lines skipped,
    compaction) and the PLANNED->LOADING->WARMED->ROUTED->DRAINED->DONE
    state machine: load+warm BEFORE commit BEFORE drain, availability
    floor, cancel leaves a consistent prefix.
  * Controller restart mid-rebalance: a SimulatedCrash armed at
    `controller.rebalance.move` (where stage=commit) kills the engine
    between LOADING and ROUTED; a new Rebalancer on the same journal
    resumes WITHOUT re-executing finished loads and converges to the
    exact target. A torn `controller.rebalance.journal` write replays
    as skip-line, never a corrupt plan.
  * Same-seed chaos runs replay byte-identical journals.
  * RepairChecker: two-tick debounce, flap immunity, residency-preferred
    targets, `controller.repair.replicate` chaos = skip-this-tick.
  * MiniCluster end to end: live rebalance and kill+repair with zero
    failed queries and correct results throughout; replication gauges
    drain to zero on convergence; /debug/health `replication` verdict.
  * Retention closes the loop: expired segments stop being served AND
    their broker-cache entries go unaddressable (routing-epoch bump).
  * REST async jobs: POST /tables/{t}/rebalance, GET /rebalance/{jobId},
    cancel.
"""
import hashlib
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pinot_tpu.controller import ClusterState, Controller, SegmentState
from pinot_tpu.controller.cluster_state import InstanceState
from pinot_tpu.controller.rebalancer import (
    MoveJournal, Rebalancer)
from pinot_tpu.controller.repair import (
    RepairChecker, update_replication_gauges)
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import (
    FailpointError, FaultSchedule, SimulatedCrash, failpoints)
from pinot_tpu.utils.metrics import MetricsRegistry


def make_schema():
    return Schema("rb", [
        FieldSpec("d", DataType.STRING),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        FieldSpec("m", DataType.LONG, FieldType.METRIC),
    ])


def make_config(replication=1, **kw):
    tc = TableConfig("rb", TableType.OFFLINE)
    tc.retention.time_column = "ts"
    tc.retention.replication = replication
    for k, v in kw.items():
        setattr(tc.retention, k, v)
    return tc


def make_state(n_servers=3, replication=2, n_segments=3):
    st = ClusterState()
    for i in range(n_servers):
        st.register_instance(InstanceState(f"server_{i}"))
    st.add_table(make_config(replication=replication), make_schema())
    for i in range(n_segments):
        st.upsert_segment(SegmentState(
            f"s{i}", "rb_OFFLINE",
            [f"server_{j % n_servers}" for j in (i, i + 1)][:replication],
            dir_path=f"/deep/s{i}"))
    return st


class _Recorder:
    """Fake load/unload/commit endpoints that log call order."""

    def __init__(self, fail_loads=(), registry=None):
        self.calls = []
        self.fail_loads = set(fail_loads)
        self._lock = threading.Lock()

    def load(self, instance_id, table, st):
        with self._lock:
            self.calls.append(("load", instance_id, st.name if st else None))
        if instance_id in self.fail_loads:
            raise RuntimeError(f"load refused on {instance_id}")

    def unload(self, instance_id, table, name):
        with self._lock:
            self.calls.append(("unload", instance_id, name))

    def commit(self, table, assignment):
        with self._lock:
            self.calls.append(
                ("commit", tuple(sorted(assignment)),
                 {k: tuple(v) for k, v in assignment.items()}))

    def ops(self, kind):
        return [c for c in self.calls if c[0] == kind]


def make_rebalancer(st, rec, journal_path=None, overrides=None, **kw):
    cfg = PinotConfiguration().with_overrides(overrides or {})
    return Rebalancer(st, load_fn=rec.load, unload_fn=rec.unload,
                      commit_fn=rec.commit, config=cfg,
                      journal_path=journal_path,
                      metrics=MetricsRegistry("controller"), **kw)


# ---------------------------------------------------------------------------
# journal discipline
# ---------------------------------------------------------------------------

class TestMoveJournal:
    def test_last_snapshot_wins(self, tmp_path):
        j = MoveJournal(str(tmp_path / "j"))
        for state in ("PLANNED", "LOADING", "WARMED"):
            j.append({"kind": "move", "job": "a", "segment": "s0",
                      "state": state})
        j.append({"kind": "job", "job": "a", "status": "RUNNING"})
        j.close()
        out = MoveJournal(str(tmp_path / "j")).replay()
        assert len(out) == 2
        move = next(e for e in out if e["kind"] == "move")
        assert move["state"] == "WARMED"

    def test_torn_line_skipped_not_fatal(self, tmp_path):
        path = tmp_path / "j"
        j = MoveJournal(str(path))
        j.append({"kind": "move", "job": "a", "segment": "s0",
                  "state": "DONE"})
        j.close()
        with open(path, "ab") as f:  # torn tail: half a line, no newline
            f.write(b'{"kind":"move","job":"a","seg')
        out = MoveJournal(str(path)).replay()
        assert [e["state"] for e in out] == ["DONE"]

    def test_compaction_preserves_latest(self, tmp_path):
        path = tmp_path / "j"
        j = MoveJournal(str(path), max_bytes=256)
        for i in range(50):
            j.append({"kind": "move", "job": "a", "segment": "s0",
                      "state": f"S{i}"})
        j.close()
        assert path.stat().st_size < 4096  # compacted, not 50 lines
        out = MoveJournal(str(path)).replay()
        assert out[-1]["state"] == "S49"


class TestStagedReplicas:
    def test_stage_commit_unstage(self):
        st = make_state(n_servers=3, replication=1, n_segments=1)
        st.stage_replicas("rb_OFFLINE", {"s0": ["server_2"]})
        seg = st.table_segments("rb_OFFLINE")[0]
        assert seg.staged == ["server_2"]
        assert "server_2" not in seg.instances  # brokers route instances only
        st.commit_moves("rb_OFFLINE", {"s0": ["server_2"]})
        seg = st.table_segments("rb_OFFLINE")[0]
        assert seg.instances == ["server_2"]
        assert seg.staged == []  # promotion clears the staging mark
        st.stage_replicas("rb_OFFLINE", {"s0": ["server_1"]})
        st.unstage_replicas("rb_OFFLINE", {"s0": ["server_1"]})
        assert st.table_segments("rb_OFFLINE")[0].staged == []

    def test_commit_moves_single_notification(self):
        st = make_state(n_servers=3, replication=1, n_segments=3)
        events = []
        st.add_listener(events.append)
        st.commit_moves("rb_OFFLINE", {"s0": ["server_2"],
                                       "s1": ["server_2"]})
        assert events == ["rb_OFFLINE"]  # one batch = one epoch bump


# ---------------------------------------------------------------------------
# the move engine
# ---------------------------------------------------------------------------

class TestRebalancerEngine:
    def test_load_then_commit_then_drain_order(self, tmp_path):
        st = make_state()
        rec = _Recorder()
        rb = make_rebalancer(st, rec, str(tmp_path / "j"))
        job = rb.run("rb_OFFLINE", {
            "s0": {"from": ["server_0", "server_1"],
                   "to": ["server_1", "server_2"]}})
        assert job.status == "DONE"
        kinds = [c[0] for c in rec.calls]
        assert kinds == ["load", "commit", "unload"]
        # only the NEW replica loads, only the RETIRED one drains
        assert rec.ops("load")[0][1] == "server_2"
        assert rec.ops("unload")[0][1] == "server_0"
        assert st.table_segments("rb_OFFLINE") != []

    def test_no_op_move_touches_nothing(self, tmp_path):
        st = make_state()
        rec = _Recorder()
        rb = make_rebalancer(st, rec, str(tmp_path / "j"))
        job = rb.run("rb_OFFLINE", {
            "s0": {"from": ["server_0", "server_1"],
                   "to": ["server_0", "server_1"]}})
        assert job.status == "DONE"
        assert rec.ops("load") == [] and rec.ops("unload") == []

    def test_availability_floor_retains_source(self, tmp_path):
        st = make_state()
        rec = _Recorder()
        # target replicas are NOT live: draining the source would leave
        # zero live copies -> the engine must keep it
        rb = make_rebalancer(st, rec, str(tmp_path / "j"),
                             live_fn=lambda iid: iid == "server_0")
        job = rb.run("rb_OFFLINE", {
            "s0": {"from": ["server_0"], "to": ["server_2"]}})
        assert job.status == "DONE"
        assert rec.ops("unload") == []
        assert "availability floor" in job.moves[0].note

    def test_dead_source_never_unloaded_over_wire(self, tmp_path):
        st = make_state()
        rec = _Recorder()
        rb = make_rebalancer(st, rec, str(tmp_path / "j"),
                             live_fn=lambda iid: iid != "server_0")
        job = rb.run("rb_OFFLINE", {
            "s0": {"from": ["server_0", "server_1"],
                   "to": ["server_1", "server_2"]}})
        assert job.status == "DONE"
        assert rec.ops("unload") == []  # dead source: nothing to call
        assert rec.ops("commit") != []  # but the flip still happened

    def test_cancel_leaves_consistent_prefix(self, tmp_path):
        st = ClusterState()
        for i in range(3):
            st.register_instance(InstanceState(f"server_{i}"))
        st.add_table(make_config(), make_schema())
        for i in range(6):
            st.upsert_segment(SegmentState(f"s{i}", "rb_OFFLINE",
                                           ["server_0"], dir_path="/d"))
        rec = _Recorder()
        rb = make_rebalancer(
            st, rec, str(tmp_path / "j"),
            overrides={"pinot.controller.rebalance.max.parallel.moves": 1})
        moves = {f"s{i}": {"from": ["server_0"], "to": ["server_1"]}
                 for i in range(6)}
        job = rb._register("rb_OFFLINE", moves)
        job.cancel()  # cancelled before the engine starts a batch
        rb.execute(job)
        assert job.status == "CANCELLED"
        assert all(m.state == "CANCELLED" for m in job.moves)
        assert rec.ops("commit") == []  # nothing half-applied
        # journal agrees: a fresh engine sees the terminal job
        rb.close()
        rb2 = make_rebalancer(st, _Recorder(), str(tmp_path / "j"))
        assert rb2.jobs[job.job_id].status == "CANCELLED"
        assert rb2.resume() == []

    def test_deterministic_job_ids(self, tmp_path):
        st = make_state()
        rb = make_rebalancer(st, _Recorder(), str(tmp_path / "j"))
        a = rb.run("rb_OFFLINE", {"s0": {"from": ["server_0"],
                                         "to": ["server_1"]}})
        b = rb.run("rb_OFFLINE", {"s1": {"from": ["server_1"],
                                         "to": ["server_2"]}})
        assert a.job_id == "rebalance_rb_OFFLINE_0"
        assert b.job_id == "rebalance_rb_OFFLINE_1"

    def test_batched_commits(self, tmp_path):
        st = ClusterState()
        for i in range(3):
            st.register_instance(InstanceState(f"server_{i}"))
        st.add_table(make_config(), make_schema())
        for i in range(5):
            st.upsert_segment(SegmentState(f"s{i}", "rb_OFFLINE",
                                           ["server_0"], dir_path="/d"))
        rec = _Recorder()
        rb = make_rebalancer(
            st, rec, str(tmp_path / "j"),
            overrides={"pinot.controller.rebalance.max.parallel.moves": 2})
        moves = {f"s{i}": {"from": ["server_0"], "to": ["server_1"]}
                 for i in range(5)}
        job = rb.run("rb_OFFLINE", moves)
        assert job.status == "DONE"
        # 5 moves at max_parallel=2 -> ceil(5/2)=3 batch commits, each a
        # single routing-epoch bump covering its whole batch
        commits = rec.ops("commit")
        assert [len(c[1]) for c in commits] == [2, 2, 1]


# ---------------------------------------------------------------------------
# restart / crash / torn-write resilience (the chaos satellites)
# ---------------------------------------------------------------------------

class TestCrashResume:
    def test_restart_mid_rebalance_resumes_from_journal(self, tmp_path):
        """Kill the controller between LOADING and ROUTED (the armed
        crash fires at the commit stage); a NEW engine on the same
        journal resumes: finished loads are NOT re-executed, the plan
        converges to the exact target."""
        st = make_state(n_servers=3, replication=1, n_segments=2)
        rec = _Recorder()
        jp = str(tmp_path / "j")
        rb = make_rebalancer(
            st, rec, jp,
            overrides={"pinot.controller.rebalance.max.parallel.moves": 1})
        moves = {"s0": {"from": ["server_0"], "to": ["server_1"]},
                 "s1": {"from": ["server_1"], "to": ["server_2"]}}
        with failpoints.armed("controller.rebalance.move",
                              error=SimulatedCrash("controller died"),
                              where={"stage": "commit"}, times=1):
            with pytest.raises(SimulatedCrash):
                rb.run("rb_OFFLINE", moves)
        rb.close()
        # crash window: s0 loaded+WARMED but never committed
        assert ("load", "server_1", "s0") in rec.calls
        assert rec.ops("commit") == []
        # "restart": fresh engine, fresh endpoints, same journal
        rec2 = _Recorder()
        rb2 = make_rebalancer(
            st, rec2, jp,
            overrides={"pinot.controller.rebalance.max.parallel.moves": 1})
        resumed = rb2.resume()
        assert len(resumed) == 1
        job = rb2.jobs[resumed[0]]
        assert job.status == "DONE"
        # s0 was already WARMED -> resume must NOT reload it
        assert ("load", "server_1", "s0") not in rec2.calls
        assert ("load", "server_2", "s1") in rec2.calls
        # exact target reached, both segments committed
        committed = {}
        for c in rec2.ops("commit"):
            committed.update(c[2])
        assert committed == {"s0": ("server_1",), "s1": ("server_2",)}
        rb2.close()

    def test_torn_journal_write_resumes_not_corrupts(self, tmp_path):
        """A torn journal line (armed at controller.rebalance.journal)
        replays as skip-line: the move's LAST GOOD snapshot wins and
        resume re-executes the lost idempotent transition."""
        st = make_state(n_servers=3, replication=1, n_segments=1)
        rec = _Recorder()
        jp = str(tmp_path / "j")
        rb = make_rebalancer(st, rec, jp)
        # tear the move's final DONE snapshot as it is written
        with failpoints.armed("controller.rebalance.journal", torn=True,
                              where={"kind": "move", "state": "DONE"},
                              times=1):
            job = rb.run("rb_OFFLINE", {"s0": {"from": ["server_0"],
                                               "to": ["server_1"]}})
        assert job.status == "DONE"
        rb.close()
        # the job line said DONE, the move's DONE line tore -> replay
        # falls back to DRAINED; a fresh engine sees a consistent plan
        rb2 = make_rebalancer(st, _Recorder(), jp)
        assert rb2.jobs[job.job_id].moves[0].state == "DRAINED"
        assert rb2.jobs[job.job_id].status == "DONE"
        rb2.close()

    def test_crash_at_drain_resumes_without_reload_or_recommit(
            self, tmp_path):
        """Engine dies AFTER commit (stage=drain): the journal says
        ROUTED, so resume neither reloads nor recommits — it only
        finishes the drain."""
        st = make_state(n_servers=3, replication=1, n_segments=1)
        rec = _Recorder()
        jp = str(tmp_path / "j")
        rb = make_rebalancer(st, rec, jp)
        with failpoints.armed("controller.rebalance.move",
                              error=SimulatedCrash("died at drain"),
                              where={"stage": "drain"}, times=1):
            with pytest.raises(SimulatedCrash):
                rb.run("rb_OFFLINE", {"s0": {"from": ["server_0"],
                                             "to": ["server_1"]}})
        rb.close()
        assert len(rec.ops("commit")) == 1
        rec2 = _Recorder()
        rb2 = make_rebalancer(st, rec2, jp)
        assert rb2.jobs and rb2.resume()
        job = next(iter(rb2.jobs.values()))
        assert job.status == "DONE"
        assert job.moves[0].state == "DONE"
        assert rec2.ops("load") == []    # load not re-executed
        assert rec2.ops("commit") == []  # routing not flipped twice
        assert rec2.ops("unload") == [("unload", "server_0", "s0")]
        rb2.close()

    def test_same_seed_chaos_replays_byte_identical_journal(self, tmp_path):
        """Two runs of the same plan under the same seeded FaultSchedule
        produce byte-identical decision journals (no timestamps, no
        uuids, deterministic job ids + execution order)."""
        def one_run(sub, seed):
            st = make_state(n_servers=3, replication=1, n_segments=3)
            rec = _Recorder()
            jp = str(tmp_path / sub)
            rb = make_rebalancer(st, rec, jp, overrides={
                "pinot.controller.rebalance.max.parallel.moves": 1})
            sched = FaultSchedule([
                ("controller.rebalance.move",
                 {"delay": 0.003, "probability": 0.5, "seed": seed}),
            ])
            sched.arm()
            try:
                job = rb.run("rb_OFFLINE", {
                    f"s{i}": {"from": [f"server_{i % 3}"],
                              "to": [f"server_{(i + 1) % 3}"]}
                    for i in range(3)})
            finally:
                sched.disarm()
                rb.close()
            assert job.status == "DONE"
            with open(jp, "rb") as f:
                return hashlib.sha1(f.read()).hexdigest(), sched.decisions()

        h1, d1 = one_run("run1", seed=42)
        h2, d2 = one_run("run2", seed=42)
        assert h1 == h2
        assert d1 == d2


# ---------------------------------------------------------------------------
# automatic failure repair
# ---------------------------------------------------------------------------

class _FakeAges:
    def __init__(self):
        self.ages = {}

    def __call__(self):
        return dict(self.ages)


def make_repair(st, rec=None, grace=1.0, overrides=None, journal=None):
    rec = rec or _Recorder()
    cfg = PinotConfiguration().with_overrides({
        "pinot.controller.repair.grace.seconds": grace,
        **(overrides or {})})

    def commit(table, assignment):  # record AND apply
        rec.commit(table, assignment)
        st.commit_moves(table, assignment)

    rb = Rebalancer(st, load_fn=rec.load, unload_fn=rec.unload,
                    commit_fn=commit, config=cfg, journal_path=journal,
                    metrics=MetricsRegistry("controller"))
    ages = _FakeAges()
    rep = RepairChecker(st, rb, ages, config=cfg,
                        metrics=MetricsRegistry("controller"))
    return rep, rb, rec, ages


class TestRepairChecker:
    def test_two_tick_debounce(self):
        st = make_state()
        rep, _rb, rec, ages = make_repair(st)
        ages.ages = {"server_0": 5.0, "server_1": 0.0, "server_2": 0.0}
        first = rep.check_once()
        assert first["stale"] == [] and first["repaired"] == {}
        assert rec.ops("load") == []  # one stale tick repairs NOTHING
        second = rep.check_once()
        assert second["stale"] == ["server_0"]
        assert second["repaired"] != {}

    def test_flapping_instance_never_triggers_churn(self):
        st = make_state()
        rep, _rb, rec, ages = make_repair(st)
        for _ in range(4):  # stale, fresh, stale, fresh ...
            ages.ages = {"server_0": 5.0}
            assert rep.check_once()["repaired"] == {}
            ages.ages = {"server_0": 0.0}
            assert rep.check_once()["repaired"] == {}
        assert rec.ops("load") == []

    def test_rejoin_after_repair_costs_zero_moves(self):
        st = make_state(n_servers=3, replication=2, n_segments=2)
        rep, _rb, rec, ages = make_repair(st)
        ages.ages = {"server_0": 9.0}
        rep.check_once()
        out = rep.check_once()
        assert out["repaired"] != {}
        n_loads = len(rec.ops("load"))
        ages.ages = {"server_0": 0.0}  # the instance comes back
        for _ in range(2):
            assert rep.check_once()["repaired"] == {}
        assert len(rec.ops("load")) == n_loads  # nothing moved back

    def test_targets_prefer_residency(self):
        st = ClusterState()
        st.register_instance(InstanceState("server_0"))
        st.register_instance(InstanceState(
            "server_cold", residency={}))
        st.register_instance(InstanceState(
            "server_warm", residency={"rb_OFFLINE": 1 << 30}))
        st.add_table(make_config(replication=2), make_schema())
        st.upsert_segment(SegmentState("s0", "rb_OFFLINE",
                                       ["server_0", "server_dead"],
                                       dir_path="/d"))
        rep, _rb, rec, ages = make_repair(st)
        ages.ages = {"server_dead": 9.0}
        rep.check_once()
        out = rep.check_once()
        assert out["repaired"] == {"rb_OFFLINE": ["s0"]}
        assert rec.ops("load")[0][1] == "server_warm"

    def test_no_dir_path_skipped(self):
        st = ClusterState()
        for i in range(2):
            st.register_instance(InstanceState(f"server_{i}"))
        st.add_table(make_config(replication=2), make_schema())
        st.upsert_segment(SegmentState("s0", "rb_OFFLINE",
                                       ["server_0", "server_9"]))  # no dir
        rep, _rb, rec, ages = make_repair(st)
        ages.ages = {"server_9": 9.0}
        rep.check_once()
        assert rep.check_once()["repaired"] == {}

    def test_disabled_knob(self):
        st = make_state()
        rep, _rb, rec, ages = make_repair(
            st, overrides={"pinot.controller.repair.enabled": False})
        ages.ages = {"server_0": 99.0}
        for _ in range(3):
            assert rep.check_once() == {"stale": [], "repaired": {}}

    def test_replicate_failpoint_skips_then_retries(self):
        """An armed error at controller.repair.replicate skips the
        segment THIS tick; the next tick (failpoint exhausted) repairs
        it — chaos in the repair path degrades to retry, never crash."""
        st = make_state(n_servers=3, replication=2, n_segments=1)
        rep, _rb, rec, ages = make_repair(st)
        ages.ages = {"server_0": 9.0}
        rep.check_once()
        with failpoints.armed("controller.repair.replicate",
                              error=FailpointError("deep store hiccup"),
                              times=1):
            out = rep.check_once()
        assert out["stale"] == ["server_0"] and out["repaired"] == {}
        out = rep.check_once()
        assert out["repaired"] != {}

    def test_gauges_track_convergence(self):
        st = make_state(n_servers=3, replication=2, n_segments=2)
        reg = MetricsRegistry("controller")
        rep, _rb, _rec, ages = make_repair(st)
        rep.metrics = reg
        ages.ages = {"server_0": 9.0}
        rep.check_once()
        rep.check_once()
        gauges = reg.sample()["gauges"]
        assert gauges['segments_missing_replicas{table="rb_OFFLINE"}'] == 0


# ---------------------------------------------------------------------------
# health plane: the replication subsystem
# ---------------------------------------------------------------------------

class TestHealthReplication:
    def test_replication_subsystem_verdict(self):
        from pinot_tpu.health.rollup import role_health_summary
        reg = MetricsRegistry("controller")
        st = make_state(n_servers=3, replication=2, n_segments=2)
        update_replication_gauges(st, metrics=reg)
        ok = role_health_summary("controller", registry=reg)
        assert ok["subsystems"]["replication"]["ok"] is True
        assert "replication" not in ok["degraded"]
        # a dead holder flips the verdict...
        update_replication_gauges(st, metrics=reg,
                                  live={"server_1", "server_2"})
        bad = role_health_summary("controller", registry=reg)
        sub = bad["subsystems"]["replication"]
        assert sub["ok"] is False
        assert sub["underReplicated"] == ["rb_OFFLINE"]
        assert sub["segmentsMissingReplicas"] > 0
        # ...and convergence (missing back to 0) restores it
        update_replication_gauges(st, metrics=reg)
        again = role_health_summary("controller", registry=reg)
        assert again["subsystems"]["replication"]["ok"] is True

    def test_roles_without_gauges_grow_no_subsystem(self):
        from pinot_tpu.health.rollup import role_health_summary
        reg = MetricsRegistry("broker")
        out = role_health_summary("broker", registry=reg)
        assert "replication" not in out["subsystems"]


# ---------------------------------------------------------------------------
# REST: async rebalance jobs
# ---------------------------------------------------------------------------

class TestRebalanceHttpApi:
    @pytest.fixture()
    def rest(self, tmp_path):
        from pinot_tpu.controller.http_api import ControllerHttpServer
        st = ClusterState()
        for i in range(2):
            st.register_instance(InstanceState(f"server_{i}"))
        st.add_table(make_config(), make_schema())
        for i in range(4):  # piled on server_0: a rebalance has work
            st.upsert_segment(SegmentState(f"s{i}", "rb_OFFLINE",
                                           ["server_0"], dir_path="/d"))
        ctl = Controller(state=st,
                         rebalance_journal=str(tmp_path / "j"))
        ctl.rebalancer.metrics = MetricsRegistry("controller")
        srv = ControllerHttpServer(st, controller=ctl)
        srv.start()
        yield srv, ctl, st
        srv.stop()
        ctl.rebalancer.close()

    def _post(self, srv, path, body=None):
        req = urllib.request.Request(
            f"http://{srv.host}:{srv.port}{path}",
            data=json.dumps(body or {}).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    def _get(self, srv, path):
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}{path}", timeout=10) as r:
            return json.loads(r.read())

    def test_dry_run_then_job_lifecycle(self, rest):
        srv, ctl, st = rest
        dry = self._post(srv, "/tables/rb/rebalance", {"dryRun": True})
        assert dry["dryRun"] is True and dry["moves"]
        before = {s.name: list(s.instances)
                  for s in st.table_segments("rb_OFFLINE")}
        out = self._post(srv, "/tables/rb/rebalance", {})
        assert out["status"] == "IN_PROGRESS" and out["jobId"]
        deadline = time.time() + 10
        while time.time() < deadline:
            prog = self._get(srv, f"/rebalance/{out['jobId']}")
            if prog["status"] != "RUNNING":
                break
            time.sleep(0.02)
        assert prog["status"] == "DONE"
        assert prog["done"] == prog["totalMoves"] > 0
        after = {s.name: list(s.instances)
                 for s in st.table_segments("rb_OFFLINE")}
        assert after != before
        # balanced: and now a second POST is a NO_OP
        noop = self._post(srv, "/tables/rb/rebalance", {})
        assert noop == {"status": "NO_OP", "jobId": None}

    def test_unknown_table_404(self, rest):
        srv, _ctl, _st = rest
        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(srv, "/tables/nope/rebalance", {})
        assert e.value.code == 404

    def test_unknown_job_404_and_cancel(self, rest):
        srv, _ctl, _st = rest
        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(srv, "/rebalance/rebalance_x_0")
        assert e.value.code == 404
        out = self._post(srv, "/rebalance/rebalance_x_0/cancel")
        assert out["cancelled"] is False


# ---------------------------------------------------------------------------
# end to end on the embedded cluster
# ---------------------------------------------------------------------------

def _mini(tmp_path, num_servers=3, replication=2, n_segs=4, num_docs=400,
          **kw):
    from pinot_tpu.cluster.mini import MiniCluster
    from tests.queries.harness import (build_segments, synthetic_columns,
                                       synthetic_schema,
                                       synthetic_table_config)
    data = [synthetic_columns(num_docs, seed=11 + i) for i in range(n_segs)]
    segs = build_segments(tmp_path, synthetic_schema(),
                          synthetic_table_config(), data)
    tc = synthetic_table_config()
    tc.retention.replication = replication
    c = MiniCluster(num_servers=num_servers, **kw)
    c.start()
    c.add_table("testTable", table_config=tc, schema=synthetic_schema())
    for i, seg in enumerate(segs):
        c.add_segment("testTable", seg, server_idx=i % 2,
                      replicas=[(i + 1) % 2])
    return c, segs, num_docs * n_segs


class TestMiniClusterSelfHealing:
    def test_live_rebalance_zero_failed_queries(self, tmp_path):
        """A closed query loop runs WHILE segments move to a new server:
        every query succeeds with the exact pre-move answer, and the
        move engine never routes to the target before it loaded."""
        c, segs, total = _mini(tmp_path)
        try:
            rb = c.make_rebalancer(journal_path=str(tmp_path / "j"))
            # flip-before-load guard: at commit time every instance in
            # the assignment must already hold the segment
            inner_commit = rb.commit_fn

            def checked_commit(table, assignment):
                for name, insts in assignment.items():
                    for iid in insts:
                        srv = next(s for s in c.servers
                                   if s.instance_id == iid)
                        tdm = srv.data_manager.table(table, create=False)
                        assert tdm is not None and \
                            tdm.current_segment(name) is not None, \
                            f"routing flipped before {name} loaded on {iid}"
                inner_commit(table, assignment)

            rb.commit_fn = checked_commit
            failures, answers, stop = [], [], threading.Event()

            def client():
                while not stop.is_set():
                    try:
                        resp = c.query("SELECT COUNT(*) FROM testTable")
                        if resp.exceptions:
                            failures.append(repr(resp.exceptions))
                        else:
                            answers.append(resp.rows[0][0])
                    except Exception as exc:  # noqa: BLE001
                        failures.append(repr(exc))

            threads = [threading.Thread(target=client) for _ in range(4)]
            for t in threads:
                t.start()
            moves = {segs[i].name: {
                "from": ["server_0", "server_1"]
                if i % 2 == 0 else ["server_1", "server_0"],
                "to": ["server_1", "server_2"]} for i in range(len(segs))}
            job = rb.run("testTable_OFFLINE", moves)
            stop.set()
            for t in threads:
                t.join()
            rb.close()
            assert job.status == "DONE"
            assert failures == []
            assert answers and set(answers) == {total}
            # sources actually drained; the target now serves
            for seg in segs:
                assert c.servers[0].data_manager.table(
                    "testTable_OFFLINE").current_segment(seg.name) is None
                assert c.servers[2].data_manager.table(
                    "testTable_OFFLINE").current_segment(seg.name) is not None
        finally:
            c.stop()

    def test_kill_server_repair_converges(self, tmp_path):
        """Kill one server mid-loop: queries keep succeeding through
        broker failover, the repair checker re-replicates the dead
        server's segments, and segments_missing_replicas drains to 0.
        A roomy retry budget covers the burst of simultaneous retries
        the instant the server dies (4 clients all hit it at once)."""
        c, segs, total = _mini(
            tmp_path,
            config=PinotConfiguration().with_overrides(
                {"pinot.broker.retry.budget.min": 64.0,
                 "pinot.broker.retry.budget.cap": 128.0}))
        reg = MetricsRegistry("controller")
        try:
            rb = c.make_rebalancer(journal_path=str(tmp_path / "j"))
            rb.metrics = reg
            rep = c.make_repair_checker(rb)
            rep.metrics = reg
            rep.grace_s = 0.01
            failures, answers, stop = [], [], threading.Event()

            def client():
                while not stop.is_set():
                    try:
                        resp = c.query("SELECT COUNT(*) FROM testTable")
                        if resp.exceptions:
                            failures.append(repr(resp.exceptions))
                        else:
                            answers.append(resp.rows[0][0])
                    except Exception as exc:  # noqa: BLE001
                        failures.append(repr(exc))

            threads = [threading.Thread(target=client) for _ in range(4)]
            for t in threads:
                t.start()
            c.kill_server(0)
            time.sleep(0.05)
            deadline = time.time() + 15
            converged = None
            while time.time() < deadline:
                out = rep.check_once()
                missing = reg.sample()["gauges"].get(
                    'segments_missing_replicas{table="testTable_OFFLINE"}')
                if out["repaired"] == {} and out["stale"] and missing == 0:
                    converged = out
                    break
                time.sleep(0.02)
            stop.set()
            for t in threads:
                t.join()
            rb.close()
            assert converged is not None, "repair did not converge"
            assert failures == []
            assert answers and set(answers) == {total}
            # every segment has `replication` LIVE copies again
            for seg in c.cluster_state.table_segments("testTable_OFFLINE"):
                live = [i for i in seg.instances if i != "server_0"]
                assert len(live) >= 2, (seg.name, seg.instances)
        finally:
            c.stop()


class TestRetentionClosesTheLoop:
    def test_expired_segment_stops_serving_and_cache_unaddressable(
            self, tmp_path):
        """run_retention purges state AND servers AND routing AND broker
        caches: the expired rows disappear from results, and the cached
        whole-table answer is unaddressable (epoch moved), not stale."""
        from pinot_tpu.cluster.mini import MiniCluster
        from pinot_tpu.segment.creator import SegmentCreator
        from pinot_tpu.segment.loader import load_segment
        now = int(time.time() * 1000)
        tc = make_config(retention_time_value=1, retention_time_unit="DAYS")
        schema = make_schema()

        def build(name, ts_base, n=50):
            cols = {"d": [f"k{i % 5}" for i in range(n)],
                    "ts": (ts_base + np.arange(n)).astype(np.int64),
                    "m": np.arange(n).astype(np.int64)}
            out = str(tmp_path / name)
            SegmentCreator(tc, schema).build(cols, out, name)
            return load_segment(out)

        old = build("old", ts_base=now - 3 * 86_400_000)
        new = build("new", ts_base=now - 1000)
        c = MiniCluster(num_servers=2, result_cache=True)
        c.start()
        c.add_table("rb", time_column="ts", table_config=tc, schema=schema)
        c.add_segment("rb", old, 0)
        c.add_segment("rb", new, 1)
        try:
            r1 = c.query("SELECT COUNT(*) FROM rb")
            assert r1.rows[0][0] == 100
            r2 = c.query("SELECT COUNT(*) FROM rb")  # cached answer
            assert r2.rows[0][0] == 100
            removed = c.run_retention(now_ms=now)
            assert removed == {"rb_OFFLINE": ["old"]}
            # the expired segment is unloaded everywhere...
            for s in c.servers:
                tdm = s.data_manager.table("rb_OFFLINE", create=False)
                assert tdm is None or tdm.current_segment("old") is None
            # ...and the post-retention answer reflects it (the cached
            # 100-row entry went unaddressable with the routing epoch)
            r3 = c.query("SELECT COUNT(*) FROM rb")
            assert r3.rows[0][0] == 50
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# tier-1 smoke of the acceptance driver
# ---------------------------------------------------------------------------

class TestRebalanceBenchSmoke:
    def test_rebalance_bench_smoke(self, tmp_path):
        """The --rebalance acceptance scenario at smoke scale: live
        rebalance + kill/repair under a closed query loop with ZERO
        failed queries, and the same-seed chaos leg replays identical
        journals (the full-scale bars live in BENCH_rebalance.json)."""
        import bench
        out = str(tmp_path / "BENCH_rebalance_smoke.json")
        bench.rebalance_main(smoke=True, out_path=out)
        with open(out) as f:
            data = json.load(f)
        assert data["smoke"] is True
        assert data["rebalance"]["failed_queries"] == 0
        assert data["repair"]["failed_queries"] == 0
        assert data["repair"]["converged"] is True
        assert data["determinism"]["journals_identical"] is True
