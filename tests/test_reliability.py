"""Reliability layer: end-to-end deadlines, hedged scatter, failpoints.

ISSUE 3 acceptance: with a failpoint delaying one server past the query
deadline the broker returns within timeoutMs + epsilon with
partialResult=true and a typed 250 exception (no 60s stall); the
server-side segment loop observes the cancel and stops early; against a
delayed-but-healthy replica the hedged request wins and the aggregate
equals the unhedged result; chaos schedules reproduce exactly across two
runs with the same seed.
"""
import threading
import time

import pytest

from pinot_tpu.cluster.mini import MiniCluster
from pinot_tpu.server.query_server import ServerConnection
from pinot_tpu.server.scheduler import make_scheduler
from pinot_tpu.utils.accounting import BrokerTimeoutError
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import (FailpointError, FailpointRegistry,
                                        FaultSchedule, failpoints)
from pinot_tpu.utils.metrics import get_registry
from tests.queries.harness import (
    build_segments, synthetic_columns, synthetic_schema,
    synthetic_table_config)

NUM_SEGMENTS = 4
DOCS = 400
COUNT_SUM = "SELECT COUNT(*), SUM(intCol) FROM testTable"


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def _segments(tmp_path_factory, name):
    tmp = tmp_path_factory.mktemp(name)
    data = [synthetic_columns(DOCS, seed=11 + i) for i in range(NUM_SEGMENTS)]
    return build_segments(tmp, synthetic_schema(), synthetic_table_config(),
                          data)


def _cluster(segs, config=None, replicated=False, **kwargs):
    c = MiniCluster(num_servers=2, config=config, **kwargs)
    c.start()
    c.add_table("testTable")
    for i, seg in enumerate(segs):
        c.add_segment("testTable", seg, server_idx=i % 2,
                      replicas=[(i + 1) % 2] if replicated else ())
    return c


# ---------------------------------------------------------------------------
# Failpoint registry semantics
# ---------------------------------------------------------------------------

class TestFailpoints:
    def test_unarmed_site_passthrough(self):
        reg = FailpointRegistry()
        assert reg.hit("nope", payload=b"x") == b"x"
        assert reg.count("nope") == 0

    def test_delay_error_drop_torn(self):
        reg = FailpointRegistry()
        reg.arm("a", delay=0.05)
        t0 = time.time()
        reg.hit("a")
        assert time.time() - t0 >= 0.05
        reg.arm("b", error=FailpointError("boom"))
        with pytest.raises(FailpointError):
            reg.hit("b")
        reg.arm("c", drop=True)
        with pytest.raises(ConnectionError):
            reg.hit("c")
        reg.arm("d", torn=True)
        assert reg.hit("d", payload=b"0123456789") == b"01234"

    def test_one_shot_and_where_match(self):
        reg = FailpointRegistry()
        fp = reg.arm("s", error=FailpointError("x"), times=1,
                     where={"instance": "server_0"})
        # non-matching context never fires and never consumes the shot
        assert reg.hit("s", instance="server_1", payload=b"p") == b"p"
        with pytest.raises(FailpointError):
            reg.hit("s", instance="server_0")
        # one-shot exhausted
        assert reg.hit("s", instance="server_0", payload=b"p") == b"p"
        assert fp.fired == 1 and fp.hits == 2

    def test_probability_seeded_reproducible(self):
        def run(seed):
            reg = FailpointRegistry()
            fp = reg.arm("p", delay=0.0, probability=0.5, seed=seed)
            for _ in range(32):
                reg.hit("p")
            return [d[0] for d in fp.decisions]

        a, b = run(42), run(42)
        assert a == b  # same seed -> identical schedule
        assert any(a) and not all(a)  # the coin actually flips
        assert run(7) != a  # a different seed moves the schedule

    def test_exponential_delay_seeded(self):
        def run():
            reg = FailpointRegistry()
            fp = reg.arm("e", delay=0.001, exponential=True, seed=3)
            for _ in range(8):
                reg.hit("e")
            return [d[1] for d in fp.decisions]

        a, b = run(), run()
        assert a == b
        assert len(set(a)) > 1  # actually exponential, not fixed

    def test_armed_context_manager(self):
        with failpoints.armed("ctx.site", error=FailpointError("x")):
            with pytest.raises(FailpointError):
                failpoints.hit("ctx.site")
        assert failpoints.hit("ctx.site", payload=b"p") == b"p"


# ---------------------------------------------------------------------------
# Deadline propagation
# ---------------------------------------------------------------------------

#: server-side per-segment fan-out width (QueryExecutor max_threads):
#: cooperative checks run at segment START, so observing a mid-loop stop
#: needs MORE segments than worker threads — two waves, the second of
#: which must see the cancel/deadline
_POOL_WIDTH = 8
_MANY_SEGMENTS = 12


@pytest.mark.chaos
class TestDeadlines:
    @pytest.fixture(scope="class")
    def cluster(self, tmp_path_factory):
        c = _cluster(_segments(tmp_path_factory, "deadline"))
        # a 12-segment table pinned to server_0: per-segment chaos gets
        # two execution waves there (12 > the 8-thread segment pool)
        tmp = tmp_path_factory.mktemp("deadline_many")
        many = build_segments(
            tmp, synthetic_schema(), synthetic_table_config(),
            [synthetic_columns(50, seed=100 + i)
             for i in range(_MANY_SEGMENTS)])
        c.add_table("manyTable")
        for seg in many:
            c.add_segment("manyTable", seg, server_idx=0)
        yield c
        c.stop()

    def test_deadline_expiry_returns_partial_not_hang(self, cluster):
        """One server stuck past the budget: the broker answers within
        timeoutMs + epsilon with partialResult + a typed 250, and the
        healthy server's rows are present."""
        with failpoints.armed("server.execute.before", delay=3.0,
                              where={"instance": "server_0"}):
            t0 = time.time()
            resp = cluster.query(COUNT_SUM + " OPTION(timeoutMs=300)")
            elapsed = time.time() - t0
        assert elapsed < 1.0, f"stalled {elapsed:.2f}s past the deadline"
        assert resp.partial_result is True
        codes = [e["errorCode"] for e in resp.exceptions]
        assert 250 in codes
        assert "BrokerTimeoutError" in resp.exceptions[0]["message"]
        # the healthy replica's partial made it into the answer
        assert resp.rows[0][0] == DOCS * (NUM_SEGMENTS // 2)
        assert resp.num_servers_queried == 2
        assert resp.num_servers_responded == 1

    def test_clean_run_is_not_partial(self, cluster):
        resp = cluster.query(COUNT_SUM + " OPTION(timeoutMs=30000)")
        assert resp.exceptions == [] and resp.partial_result is False
        assert resp.rows[0][0] == DOCS * NUM_SEGMENTS

    def test_deadline_observed_mid_segment_loop(self, cluster):
        """Per-segment delays on a 12-segment server: the shipped
        remaining budget expires between the first and second execution
        wave, so the loop's cooperative check stops it — the server
        answers a typed 250 without finishing every segment."""
        with failpoints.armed("server.execute.segment", delay=0.5) as fp:
            resp = cluster.query(
                "SELECT COUNT(*) FROM manyTable OPTION(timeoutMs=300)")
            # wave 1 (8 segments) is already in flight when the budget
            # expires; wave 2's segment-start checks must all refuse
            assert fp.fired <= _POOL_WIDTH, \
                f"segment loop ran past the deadline ({fp.fired} fired)"
        assert resp.partial_result is True
        assert any(e["errorCode"] == 250 for e in resp.exceptions)

    def test_broker_cancel_stops_server_segment_loop(self, cluster):
        """Out-of-band cancel (the broker-expiry message) observed by the
        segment loop: the blocked request returns a 250 promptly and the
        second execution wave never runs."""
        server = cluster.servers[0]
        conn = ServerConnection(server.transport.host, server.transport.port)
        try:
            done = []
            with failpoints.armed("server.execute.segment",
                                  delay=0.3) as fp:
                t = threading.Thread(
                    target=lambda: done.append(conn.request(
                        "manyTable_OFFLINE",
                        "SELECT COUNT(*) FROM manyTable", None,
                        request_id=991, query_id="cancel-me")))
                t.start()
                time.sleep(0.15)  # wave 1 is mid-sleep
                cancel_conn = ServerConnection(server.transport.host,
                                               server.transport.port)
                assert cancel_conn.cancel("cancel-me") is True
                cancel_conn.close()
                t.join(timeout=5)
                assert not t.is_alive(), "cancel did not unblock the query"
                assert fp.fired <= _POOL_WIDTH, \
                    "segment loop ran past the cancel"
            from pinot_tpu.server import datatable
            _results, exc, _stats = datatable.deserialize_results(done[0])
            assert any(e["errorCode"] == 250 for e in exc)
        finally:
            conn.close()

    def test_scheduler_refuses_expired_queue_work(self):
        sched = make_scheduler("fcfs", 2)
        try:
            fut = sched.submit(lambda: b"ran", deadline=time.time() - 1.0)
            with pytest.raises(BrokerTimeoutError):
                fut.result(timeout=5)
            # a live deadline still runs
            fut = sched.submit(lambda: b"ran", deadline=time.time() + 5.0)
            assert fut.result(timeout=5) == b"ran"
        finally:
            sched.stop()

    def test_client_surfaces_typed_timeout_with_partial(self,
                                                        tmp_path_factory):
        """DB-API client: a deadline miss raises PinotTimeoutError (not a
        generic failure) and carries the broker's partial rows."""
        from pinot_tpu.client.connection import PinotTimeoutError, connect
        segs = _segments(tmp_path_factory, "client_deadline")
        c = MiniCluster(num_servers=2)
        c.start(with_http=True)
        c.add_table("testTable")
        for i, seg in enumerate(segs):
            c.add_segment("testTable", seg, server_idx=i % 2)
        try:
            conn = connect(f"127.0.0.1:{c.http.port}")
            assert conn.execute(COUNT_SUM).rows[0][0] == DOCS * NUM_SEGMENTS
            with failpoints.armed("server.execute.before", delay=3.0,
                                  where={"instance": "server_0"}):
                with pytest.raises(PinotTimeoutError) as exc_info:
                    conn.execute(COUNT_SUM, timeout_ms=300)
            rs = exc_info.value.result_set
            assert rs is not None and rs.partial_result is True
            assert rs.rows[0][0] == DOCS * (NUM_SEGMENTS // 2)
        finally:
            c.stop()

    def test_set_statement_timeout(self, cluster):
        """SET timeoutMs (the client Connection's channel) binds the
        budget exactly like OPTION(...)."""
        with failpoints.armed("server.execute.before", delay=3.0,
                              where={"instance": "server_1"}):
            t0 = time.time()
            resp = cluster.query(f"SET timeoutMs = 300; {COUNT_SUM}")
            elapsed = time.time() - t0
        assert elapsed < 1.0 and resp.partial_result is True


# ---------------------------------------------------------------------------
# Hedged scatter
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestHedging:
    @pytest.fixture(scope="class")
    def cluster(self, tmp_path_factory):
        cfg = PinotConfiguration(overrides={
            "pinot.broker.hedge.enabled": True,
            "pinot.broker.hedge.delay.min.ms": 60,
        })
        c = _cluster(_segments(tmp_path_factory, "hedge"), config=cfg,
                     replicated=True)
        yield c
        c.stop()

    def _meters(self):
        m = get_registry("broker")
        return {name: m.meter(name)
                for name in ("hedge_issued", "hedge_won", "hedge_wasted")}

    def test_hedge_wins_against_delayed_replica(self, cluster):
        base = cluster.query(COUNT_SUM)
        assert base.exceptions == []
        before = self._meters()
        with failpoints.armed("server.execute.before", delay=1.5,
                              where={"instance": "server_0"}):
            t0 = time.time()
            resp = cluster.query(COUNT_SUM)
            elapsed = time.time() - t0
        after = self._meters()
        # the hedge rescued the latency AND the aggregate is bit-equal
        # to the unhedged answer — duplicates never double-merge
        assert elapsed < 1.0, f"hedge did not rescue: {elapsed:.2f}s"
        assert resp.rows == base.rows
        assert resp.exceptions == [] and resp.partial_result is False
        assert after["hedge_issued"] >= before["hedge_issued"] + 1
        assert after["hedge_won"] >= before["hedge_won"] + 1

    def test_hedge_loses_cleanly_against_fast_primary(self, cluster):
        """Primary slower than the hedge delay but faster than the hedge
        replica: the primary wins, the duplicate is discarded, and the
        aggregate still equals the unhedged answer."""
        base = cluster.query(COUNT_SUM)
        before = self._meters()
        # server_0 (primary for half the segments) is slow enough to
        # trigger hedging but beats the even-slower hedge target
        with failpoints.armed("server.execute.before", delay=0.2,
                              where={"instance": "server_0"}), \
             failpoints.armed("server.execute.before", delay=1.0,
                              where={"instance": "server_1"}):
            resp = cluster.query(
                COUNT_SUM + " OPTION(timeoutMs=10000)")
        after = self._meters()
        assert resp.rows == base.rows
        assert resp.exceptions == [] and resp.partial_result is False
        assert after["hedge_issued"] >= before["hedge_issued"] + 1
        assert after["hedge_wasted"] >= before["hedge_wasted"] + 1

    def test_errored_hedge_holds_for_clean_primary(self, tmp_path_factory):
        """First CLEAN response wins: a hedge that instantly answers with
        an in-payload error must not beat a slow-but-healthy primary —
        the errored payload is held back and the clean twin merges."""
        cfg = PinotConfiguration(overrides={
            "pinot.broker.hedge.enabled": True,
            "pinot.broker.hedge.delay.min.ms": 60,
        })
        segs = _segments(tmp_path_factory, "hedge_fallback")
        c = MiniCluster(num_servers=2, config=cfg)
        c.start()
        c.add_table("testTable")
        # ONE segment, primary on server_0 (fresh route, rr=0), replica
        # on server_1 — the hedge target is deterministic
        c.add_segment("testTable", segs[0], server_idx=0, replicas=[1])
        try:
            with failpoints.armed("server.execute.before", delay=0.3,
                                  where={"instance": "server_0"}), \
                 failpoints.armed("server.execute.before",
                                  error=FailpointError("hedge replica bad"),
                                  where={"instance": "server_1"}):
                resp = c.query("SELECT COUNT(*) FROM testTable")
            assert resp.rows[0][0] == DOCS
            assert resp.exceptions == [] and resp.partial_result is False
        finally:
            c.stop()

    def test_hedged_duplicates_never_double_count(self, cluster):
        """Both replicas answer (one late): COUNT must match exactly —
        the canonical double-merge symptom would be 2x."""
        base = cluster.query("SELECT COUNT(*) FROM testTable")
        with failpoints.armed("server.execute.before", delay=0.15,
                              where={"instance": "server_1"}):
            for _ in range(3):
                resp = cluster.query("SELECT COUNT(*) FROM testTable")
                assert resp.rows == base.rows


# ---------------------------------------------------------------------------
# MiniCluster chaos schedules
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestChaosSchedules:
    def _run(self, segs, seed):
        sched = FaultSchedule([
            ("server.execute.before",
             {"error": FailpointError("chaos"), "probability": 0.5,
              "seed": seed, "where": {"instance": "server_0"}}),
        ])
        c = _cluster(segs, chaos=sched)
        try:
            outcomes = []
            for _ in range(12):
                resp = c.query("SELECT COUNT(*) FROM testTable")
                outcomes.append(bool(resp.exceptions))
            return outcomes, sched.decisions()
        finally:
            c.stop()

    def test_schedule_reproducible_across_runs(self, tmp_path_factory):
        segs = _segments(tmp_path_factory, "chaos")
        out_a, dec_a = self._run(segs, seed=1234)
        out_b, dec_b = self._run(segs, seed=1234)
        assert dec_a == dec_b, "same seed must replay the same schedule"
        assert out_a == out_b, "same schedule must produce the same outcomes"
        assert any(out_a) and not all(out_a)
        out_c, dec_c = self._run(segs, seed=99)
        assert dec_c != dec_a


# ---------------------------------------------------------------------------
# Negative cache (pruned-to-zero plans)
# ---------------------------------------------------------------------------

class TestNegativeCache:
    @pytest.fixture()
    def empty_cluster(self):
        c = MiniCluster(num_servers=1)
        c.start()
        c.add_table("emptyTable")
        yield c
        c.stop()

    def test_pruned_to_zero_memoized_epoch_keyed(self, empty_cluster,
                                                 tmp_path_factory):
        c = empty_cluster
        neg = c.broker._negative_cache
        q = "SELECT COUNT(*) FROM emptyTable"
        r1 = c.query(q)
        assert r1.exceptions == [] and r1.cache_hit is False
        assert len(neg) == 1
        r2 = c.query(q)
        assert r2.cache_hit is True  # served without routing or scatter
        assert r2.rows == r1.rows
        hits_before = neg.stats.hits
        # skipCache bypasses the memo entirely
        r3 = c.query(q + " OPTION(skipCache=true)")
        assert r3.cache_hit is False
        assert neg.stats.hits == hits_before
        # a segment arrival moves the epoch: the empty answer stops
        # being addressable by construction
        segs = _segments(tmp_path_factory, "negcache")
        c.add_segment("emptyTable", segs[0], server_idx=0)
        r4 = c.query(q)
        assert r4.cache_hit is False
        assert r4.rows[0][0] == DOCS

    def test_nonempty_plan_never_negative_cached(self, tmp_path_factory):
        segs = _segments(tmp_path_factory, "negcache2")
        c = _cluster(segs)
        try:
            c.query(COUNT_SUM)
            assert len(c.broker._negative_cache) == 0
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# FingerprintLog journal persistence
# ---------------------------------------------------------------------------

class TestFingerprintJournal:
    def _log(self, path, **kw):
        from pinot_tpu.cache.warmup import FingerprintLog
        return FingerprintLog(8, journal_path=str(path), **kw)

    def test_restart_reloads_history(self, tmp_path):
        p = tmp_path / "fp.jsonl"
        log = self._log(p)
        log.record("t1", "fp1", "SELECT 1", extra_filter="x <= 5")
        log.record("t1", "fp2", "SELECT 2")
        log.record("t2", "fp3", "SELECT 3")
        reborn = self._log(p)
        assert reborn.plans("t1") == [("fp1", "SELECT 1", "x <= 5"),
                                      ("fp2", "SELECT 2", None)]
        assert reborn.plans("t2") == [("fp3", "SELECT 3", None)]

    def test_torn_and_corrupt_lines_degrade_per_line(self, tmp_path):
        p = tmp_path / "fp.jsonl"
        log = self._log(p)
        log.record("t", "fp1", "SELECT 1")
        log.record("t", "fp2", "SELECT 2")
        with open(p, "a") as f:
            f.write('{"t": "t", "f": "fp3", "s": "SELECT 3"')  # torn tail
        reborn = self._log(p)
        assert [fp for fp, _s, _x in reborn.plans("t")] == ["fp1", "fp2"]
        # a wholly binary file degrades to empty, not an exception
        p2 = tmp_path / "junk.jsonl"
        p2.write_bytes(b"\x00\xff garbage \x00")
        assert len(self._log(p2)) == 0

    def test_journal_caps_and_compacts(self, tmp_path):
        p = tmp_path / "fp.jsonl"
        log = self._log(p, journal_max_bytes=4096)
        for i in range(400):
            log.record("t", f"fp{i}", f"SELECT {i} FROM x")
        # bounded on disk AND the reloaded view matches the live bound
        assert p.stat().st_size < 3 * 4096
        reborn = self._log(p, journal_max_bytes=4096)
        assert [e[0] for e in reborn.plans("t")] == \
               [e[0] for e in log.plans("t")]

    def test_server_warms_from_journal_after_restart(self, tmp_path_factory):
        """End to end: run queries, tear the cluster down, start a fresh
        one over the same journal dir — the new server's log already
        holds the pre-restart plans."""
        jdir = tmp_path_factory.mktemp("journal")
        cfg = PinotConfiguration(overrides={
            "pinot.server.segment.warmup.journal.dir": str(jdir)})
        segs = _segments(tmp_path_factory, "journal_segs")
        c = _cluster(segs, config=cfg)
        try:
            c.query(COUNT_SUM)
        finally:
            c.stop()
        c2 = _cluster(segs, config=cfg)
        try:
            assert len(c2.servers[0].executor.fingerprint_log) > 0
        finally:
            c2.stop()
