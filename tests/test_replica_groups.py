"""Replica-group fault domains: scatter-to-one-group routing, whole-group
kill survival, tenant isolation, split hedges, and the seeded group-kill
chaos journal (ISSUE 8).

The MiniCluster topology throughout: 4 servers, 2 replica groups —
group 0 = servers 0/1, group 1 = servers 2/3 — with every segment's
replica list in GROUP ORDER ([g0 member, g1 member]), which is the
assignment contract the broker's ReplicaGroupInstanceSelector addresses
groups through.
"""
import os
import threading
import time

import numpy as np
import pytest

from pinot_tpu.broker.routing import (
    ReplicaGroupInstanceSelector, RoutingTable, SegmentInfo, TableRoute,
    _derive_groups)
from pinot_tpu.cluster.mini import MiniCluster
from pinot_tpu.controller.assignment import (
    ReplicaGroupConfigError, assign_replica_groups, target_assignment)
from pinot_tpu.controller.cluster_state import ClusterState, InstanceState
from pinot_tpu.models.schema import Schema
from pinot_tpu.models.table_config import TableConfig
from pinot_tpu.query.context import QueryContext
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.failpoints import FaultSchedule, failpoints


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def _state(n, tenants=None):
    st = ClusterState()
    for i in range(n):
        tags = []
        if tenants and tenants[i]:
            tags = [f"tenant:{tenants[i]}"]
        st.register_instance(InstanceState(f"server_{i}", tags=tags))
    return st


# ---------------------------------------------------------------------------
# controller: typed config errors + tenant-aware pools
# ---------------------------------------------------------------------------

class TestAssignmentConfig:
    def test_non_multiple_pool_raises_typed_error(self):
        # 5 instances / 2 groups used to SILENTLY drop server_4 from
        # every group — now it is a typed config error
        st = _state(5)
        with pytest.raises(ReplicaGroupConfigError, match="do not tile"):
            assign_replica_groups(st, "t_OFFLINE", "s0",
                                  num_replica_groups=2)
        with pytest.raises(ReplicaGroupConfigError):
            target_assignment(st, "t_OFFLINE", num_replica_groups=2)

    def test_too_few_instances_raises(self):
        st = _state(1)
        with pytest.raises(ReplicaGroupConfigError):
            assign_replica_groups(st, "t_OFFLINE", "s0",
                                  num_replica_groups=2)

    def test_tenant_pool_restricts_placement(self):
        st = _state(6, tenants=["a", "a", "a", "a", "b", "b"])
        out = assign_replica_groups(st, "t_OFFLINE", "s0", 2, tenant="a")
        assert all(s in ("server_0", "server_1", "server_2", "server_3")
                   for s in out)
        out_b = assign_replica_groups(st, "t_OFFLINE", "s0", 2, tenant="b")
        assert set(out_b) == {"server_4", "server_5"}

    def test_group_order_is_stable(self):
        st = _state(4)
        from pinot_tpu.controller.cluster_state import SegmentState
        for i in range(6):
            inst = assign_replica_groups(st, "t_OFFLINE", f"s{i}", 2,
                                         partition_id=i)
            st.upsert_segment(SegmentState(f"s{i}", "t_OFFLINE",
                                           instances=inst))
        for seg in st.table_segments("t_OFFLINE"):
            assert seg.instances[0] in ("server_0", "server_1")
            assert seg.instances[1] in ("server_2", "server_3")


# ---------------------------------------------------------------------------
# broker: group selection unit behavior
# ---------------------------------------------------------------------------

def _grouped_route(n_segs=4):
    route = TableRoute("t_OFFLINE", num_replica_groups=2)
    for i in range(n_segs):
        route.segments[f"s{i}"] = SegmentInfo(
            f"s{i}", servers=[f"server_{i % 2}", f"server_{2 + i % 2}"])
    return route


class TestGroupSelector:
    def test_whole_query_lands_on_one_group(self):
        route = _grouped_route()
        rt = RoutingTable(offline=route,
                          group_selector=ReplicaGroupInstanceSelector())
        ctx = QueryContext.from_sql("SELECT COUNT(*) FROM t")
        plan = rt.route(ctx)
        servers = {e[0] for e in plan}
        assert servers <= {"server_0", "server_1"} \
            or servers <= {"server_2", "server_3"}, servers
        # every segment covered exactly once
        names = [n for e in plan for n in e[2]]
        assert sorted(names) == ["s0", "s1", "s2", "s3"]

    def test_sticky_per_fingerprint(self):
        sel = ReplicaGroupInstanceSelector()
        groups = [["a", "b"], ["c", "d"]]
        first = sel.pick_group("t", groups, set(), fingerprint="fp1")
        for _ in range(8):
            assert sel.pick_group("t", groups, set(),
                                  fingerprint="fp1") == first

    def test_unhealthy_member_demotes_whole_group(self):
        sel = ReplicaGroupInstanceSelector()
        groups = [["a", "b"], ["c", "d"]]
        g = sel.pick_group("t", groups, set(), fingerprint="fp")
        dead = groups[g][0]
        g2 = sel.pick_group("t", groups, {dead}, fingerprint="fp")
        assert g2 is not None and g2 != g  # stickiness demoted too

    def test_all_groups_degraded_returns_none(self):
        sel = ReplicaGroupInstanceSelector()
        assert sel.pick_group("t", [["a"], ["b"]], {"a", "b"}) is None

    def test_residency_breaks_ties(self):
        sel = ReplicaGroupInstanceSelector()
        sel.update_residency("c", {"t_OFFLINE": 1 << 20})
        groups = [["a", "b"], ["c", "d"]]
        for fp in ("x", "y", "z"):
            assert sel.pick_group("t_OFFLINE", groups, set(),
                                  fingerprint=fp) == 1

    def test_derive_groups_from_server_order(self):
        route = _grouped_route()
        groups = _derive_groups(list(route.segments.values()), 2)
        assert groups == [["server_0", "server_1"],
                          ["server_2", "server_3"]]

    def test_group_peers_and_index(self):
        route = _grouped_route()
        rt = RoutingTable(offline=route)
        assert rt.group_peers("t_OFFLINE", "server_0") == \
            {"server_0", "server_1"}
        assert rt.group_peers("t_OFFLINE", "server_3") == \
            {"server_2", "server_3"}
        assert rt.group_index_of("t_OFFLINE", "server_1") == 0
        assert rt.group_index_of("t_OFFLINE", "server_2") == 1
        # ungrouped tables: no fault-domain coupling
        plain = TableRoute("t_OFFLINE")
        plain.segments["s"] = SegmentInfo("s", servers=["a", "b"])
        assert RoutingTable(offline=plain).group_peers("t_OFFLINE",
                                                       "a") == set()


class TestPartitionPruning:
    def _route(self):
        route = TableRoute("t_OFFLINE")
        for part in range(4):
            route.segments[f"p{part}"] = SegmentInfo(
                f"p{part}", servers=["server_0"], partition_id=part,
                partition_column="k", num_partitions=4)
        return RoutingTable(offline=route)

    def test_eq_literal_prunes_to_one_partition(self):
        rt = self._route()
        ctx = QueryContext.from_sql("SELECT COUNT(*) FROM t WHERE k = 6")
        plan = rt.route(ctx)
        names = [n for e in plan for n in e[2]]
        assert names == ["p2"]  # 6 % 4

    def test_in_literals_prune_to_member_partitions(self):
        rt = self._route()
        ctx = QueryContext.from_sql(
            "SELECT COUNT(*) FROM t WHERE k IN (1, 5, 2)")
        plan = rt.route(ctx)
        names = sorted(n for e in plan for n in e[2])
        assert names == ["p1", "p2"]  # 1%4, 5%4 -> p1; 2%4 -> p2

    def test_non_literal_in_keeps_everything(self):
        rt = self._route()
        ctx = QueryContext.from_sql(
            "SELECT COUNT(*) FROM t WHERE k IN (1, 2) OR k = 3")
        plan = rt.route(ctx)  # OR-reachable only — not provable
        names = sorted(n for e in plan for n in e[2])
        assert names == ["p0", "p1", "p2", "p3"]


# ---------------------------------------------------------------------------
# cluster: whole-group kill under load, zero failed queries
# ---------------------------------------------------------------------------

def _build_cluster(tmp, num_segments=4, docs=400, **table_kwargs):
    schema = Schema.from_dict({
        "schemaName": "rg",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
    creator = SegmentCreator(TableConfig.from_dict(
        {"tableName": "rg", "tableType": "OFFLINE"}), schema)
    cluster = MiniCluster(num_servers=4)
    cluster.start()
    cluster.add_table("rg", num_replica_groups=2, **table_kwargs)
    for i in range(num_segments):
        rng = np.random.default_rng(i)
        d = os.path.join(str(tmp), f"rg_{i}")
        creator.build({"k": rng.integers(0, 16, docs).astype(np.int64),
                       "v": rng.integers(0, 100, docs).astype(np.int64)},
                      d, f"rg_{i}")
        cluster.add_segment("rg", load_segment(d), server_idx=i % 2,
                            replicas=[2 + i % 2])
    return cluster


class TestGroupKillSurvival:
    def test_whole_group_kill_zero_failed_queries(self, tmp_path):
        cluster = _build_cluster(tmp_path)
        try:
            truth = cluster.query("SELECT COUNT(*), SUM(v) FROM rg")
            assert not truth.exceptions
            cluster.kill_replica_group("rg", 0)
            # the FIRST post-kill query pays the failover (mid-scatter
            # connection failure -> whole-group demotion -> re-scatter
            # of the unanswered segments onto group 1) and still answers
            # cleanly and completely
            for i in range(6):
                resp = cluster.query("SELECT COUNT(*), SUM(v) FROM rg")
                assert not resp.exceptions, resp.exceptions
                assert resp.rows == truth.rows
        finally:
            cluster.stop()

    def test_kill_under_concurrent_load(self, tmp_path):
        cluster = _build_cluster(tmp_path)
        failures, lock = [], threading.Lock()
        stop_at = time.perf_counter() + 1.5

        def client(cid):
            i = cid
            while time.perf_counter() < stop_at:
                resp = cluster.query(
                    f"SELECT COUNT(*) FROM rg WHERE v >= {i % 5}")
                if resp.exceptions:
                    with lock:
                        failures.append(resp.exceptions)
                i += 4
        try:
            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.3)
            cluster.kill_replica_group("rg", 0)
            for t in threads:
                t.join()
            assert not failures, failures[:3]
        finally:
            cluster.stop()

    def test_seeded_group_chaos_replays_identically(self, tmp_path):
        """The same-seed replay contract for the group-kill journal:
        outcomes AND per-site failpoint decisions match exactly."""
        def run(seed):
            sched = FaultSchedule([
                ("broker.group.scatter",
                 {"error": ConnectionError("chaos: group 0 down"),
                  "probability": 0.5, "seed": seed,
                  "where": {"group": 0}})])
            cluster = _build_cluster(tmp_path / f"run{run.n}")
            run.n += 1
            for b in cluster.brokers:
                # pin demotion: replay must not depend on when a
                # wall-clock backoff expires
                b.failure_detector.base_backoff_s = 3600.0
                b.failure_detector.max_backoff_s = 3600.0
            sched.arm()
            try:
                outcomes = []
                for i in range(10):
                    resp = cluster.query(
                        f"SELECT COUNT(*), SUM(v) FROM rg "
                        f"WHERE v >= {i % 3}")
                    outcomes.append((len(resp.exceptions), resp.rows))
                return outcomes, sched.decisions()
            finally:
                sched.disarm()
                cluster.stop()
        run.n = 0
        a = run(7)
        b = run(7)
        assert a == b
        assert all(exc == 0 for exc, _rows in a[0]), a[0]
        # the chaos actually fired at least once (not a vacuous pass)
        assert any(fired for site in a[1] for fired, _d in site)


# ---------------------------------------------------------------------------
# tenant isolation: quotas + weighted-fair scheduling
# ---------------------------------------------------------------------------

class TestTenantIsolation:
    def test_tenant_quota_rejects_named_tenant(self, tmp_path):
        cluster = _build_cluster(tmp_path, num_segments=2, docs=50,
                                 tenant="acme")
        try:
            from pinot_tpu.broker.quota import QueryQuotaManager
            qm = QueryQuotaManager()
            qm.set_table_tenant("rg", "acme")
            qm.set_tenant_quota("acme", 2.0)
            cluster.broker.quota_manager = qm
            cluster.broker.tenants["rg"] = "acme"
            seen = []
            for _ in range(6):
                resp = cluster.query("SELECT COUNT(*) FROM rg")
                seen.append(resp.exceptions)
            rejected = [e for e in seen if e]
            assert rejected, "tenant quota never enforced"
            assert "tenant acme" in rejected[0][0]["message"]
        finally:
            cluster.stop()

    def test_quota_acquire_is_all_or_nothing(self):
        from pinot_tpu.broker.quota import QueryQuotaManager
        qm = QueryQuotaManager()
        qm.set_quota("t1", 1000.0)
        qm.set_table_tenant("t1", "a")
        qm.set_tenant_quota("a", 1.0)  # tenant cap is the tight one
        assert qm.check("t1") is None
        reason = qm.check("t1")
        assert reason is not None and "tenant a" in reason
        # the rejected attempts must NOT have drained t1's table bucket
        b = qm._buckets["t1"]
        assert b.tokens >= b.cap - 1.5

    def test_multi_table_check_charges_tenant_once(self):
        """An MSE query reading N tables of one tenant is ONE query
        against the tenant ceiling, and a rejection (any table over
        budget) drains no scope at all."""
        from pinot_tpu.broker.quota import QueryQuotaManager
        qm = QueryQuotaManager()
        for t in ("a", "b"):
            qm.set_table_tenant(t, "acme")
        qm.set_tenant_quota("acme", 4.0)
        qm.set_quota("b", 1000.0)
        # 2-table query: one tenant token, not two
        assert qm.check_many(["a", "b"]) is None
        assert qm._tenant_buckets["acme"].tokens >= 3.0
        # make b reject; neither a's tenant tokens nor b's table tokens
        # may drain on the refused attempts
        qm.set_quota("b", 0.001)
        qm._buckets["b"].tokens = 0.0
        tenant_before = qm._tenant_buckets["acme"].tokens
        for _ in range(3):
            reason = qm.check_many(["a", "b"])
            assert reason is not None and "table b" in reason
        assert qm._tenant_buckets["acme"].tokens >= tenant_before

    def test_tenant_starvation_bounded(self):
        """Tenant A floods one worker through its own table; tenant B's
        queries keep a bounded wait (weighted-fair: B's bucket stays
        full while A's drains)."""
        from pinot_tpu.server.scheduler import TokenPriorityScheduler
        s = TokenPriorityScheduler(num_threads=1,
                                   tokens_per_interval=10.0,
                                   interval_s=0.1)
        s.set_tenant_weight("A", 1.0)
        s.set_tenant_weight("B", 1.0)
        s.start()
        try:
            done = []

            def slow(tag):
                def run():
                    time.sleep(0.02)
                    done.append(tag)
                    return b""
                return run

            futs = [s.submit(slow(("A", i)), table="ta", tenant="A")
                    for i in range(25)]
            time.sleep(0.06)  # A starts burning its bucket
            futs += [s.submit(slow(("B", i)), table="tb", tenant="B")
                     for i in range(3)]
            for f in futs:
                f.result(20)
            b_last = max(i for i, t in enumerate(done) if t[0] == "B")
            a_last = max(i for i, t in enumerate(done) if t[0] == "A")
            assert b_last < a_last, done
            assert b_last < len(done) - 8, done
        finally:
            s.stop()

    def test_tenant_weight_shapes_service(self):
        """Two flooding tenants, weight 4 vs 1: the heavy-weight tenant
        gets served distinctly more often early on."""
        from pinot_tpu.server.scheduler import TokenPriorityScheduler
        s = TokenPriorityScheduler(num_threads=1,
                                   tokens_per_interval=10.0,
                                   interval_s=0.1)
        s.set_tenant_weight("big", 4.0)
        s.set_tenant_weight("small", 1.0)
        s.start()
        try:
            done = []

            def job(tag):
                def run():
                    time.sleep(0.01)
                    done.append(tag)
                    return b""
                return run

            futs = []
            for i in range(20):
                futs.append(s.submit(job("big"), table="tb", tenant="big"))
                futs.append(s.submit(job("small"), table="ts",
                                     tenant="small"))
            for f in futs:
                f.result(20)
            first_half = done[:20]
            big = sum(1 for t in first_half if t == "big")
            assert big > 10, f"weight ignored: {big}/20 early slots"
        finally:
            s.stop()

    def test_tenant_rides_the_wire(self, tmp_path):
        """The broker ships the table's tenant tag; the server scheduler
        sees it (observed via a recording scheduler shim)."""
        cluster = _build_cluster(tmp_path, num_segments=2, docs=50,
                                 tenant="acme")
        try:
            seen = []
            for srv in cluster.servers:
                sched = srv.transport.scheduler
                orig = sched.submit

                def spy(fn, table="", workload="primary", deadline=None,
                        tenant=None, _orig=orig):
                    seen.append(tenant)
                    return _orig(fn, table=table, workload=workload,
                                 deadline=deadline, tenant=tenant)
                sched.submit = spy
            resp = cluster.query("SELECT COUNT(*) FROM rg")
            assert not resp.exceptions
            assert "acme" in seen, seen
        finally:
            cluster.stop()


# ---------------------------------------------------------------------------
# split hedges (partially-replicated layouts)
# ---------------------------------------------------------------------------

class TestSplitHedges:
    def _partial_cluster(self, tmp_path):
        """3 servers; segments alternate replica pairs (0,1) / (0,2), so
        after excluding server_0 NO single server holds everything —
        the shape that forces a SPLIT hedge."""
        schema = Schema.from_dict({
            "schemaName": "ph",
            "dimensionFieldSpecs": [{"name": "k", "dataType": "LONG"}],
            "metricFieldSpecs": [{"name": "v", "dataType": "LONG"}]})
        creator = SegmentCreator(TableConfig.from_dict(
            {"tableName": "ph", "tableType": "OFFLINE"}), schema)
        from pinot_tpu.utils.config import PinotConfiguration
        cfg = PinotConfiguration(overrides={
            "pinot.broker.hedge.enabled": True,
            "pinot.broker.hedge.delay.min.ms": 40,
            "pinot.broker.hedge.delay.max.ms": 60,
        })
        cluster = MiniCluster(num_servers=3, config=cfg)
        cluster.start()
        cluster.add_table("ph")
        for i in range(4):
            rng = np.random.default_rng(i)
            d = os.path.join(str(tmp_path), f"ph_{i}")
            creator.build(
                {"k": rng.integers(0, 8, 200).astype(np.int64),
                 "v": rng.integers(0, 50, 200).astype(np.int64)},
                d, f"ph_{i}")
            # ALL primaries on server_0; replicas split across 1 and 2
            cluster.add_segment("ph", load_segment(d), server_idx=0,
                                replicas=[1 + i % 2])
        return cluster

    def test_split_hedge_covers_set_exactly_once(self, tmp_path):
        """server_0 (the only full-copy holder) is made slow; the hedge
        must SPLIT across servers 1 and 2 and the merged answer must
        equal the unhedged truth — per-segment dedup, no double count."""
        cluster = self._partial_cluster(tmp_path)
        try:
            truth = cluster.query("SELECT COUNT(*), SUM(v) FROM ph")
            assert not truth.exceptions
            # pin the balanced round-robin so the chaos query's whole
            # scatter lands on server_0 (the full-copy holder)
            cluster.routing.get_route("ph")._rr = 0
            hedged = [0]
            orig = cluster.broker._metrics.add_meter

            def meter_spy(name, value=1, labels=None):
                if name == "hedge_split":
                    hedged[0] += 1
                return orig(name, value, labels=labels)
            cluster.broker._metrics.add_meter = meter_spy
            with failpoints.armed("server.execute.before", delay=0.35,
                                  where={"instance": "server_0"}):
                resp = cluster.query("SELECT COUNT(*), SUM(v) FROM ph")
            assert not resp.exceptions, resp.exceptions
            assert resp.rows == truth.rows
            assert hedged[0] >= 1, "hedge never split"
        finally:
            cluster.stop()

    def test_overlapping_primary_discarded_after_child_win(self, tmp_path):
        """The per-segment dedup core: a fast hedge child answers its
        subset FIRST, then the slow primary's full-set answer arrives —
        it overlaps the answered segments and cannot be split, so it
        must be discarded whole; the slow second child completes the
        set. The merged answer equals the truth exactly (any double
        count would inflate COUNT/SUM)."""
        cluster = self._partial_cluster(tmp_path)
        try:
            truth = cluster.query("SELECT COUNT(*), SUM(v) FROM ph")
            cluster.routing.get_route("ph")._rr = 0  # primary = server_0
            # primary mid-speed, child s1 fast, child s2 slowest:
            # arrival order = child1 (merge), primary (overlap discard),
            # child2 (complete)
            with failpoints.armed("server.execute.before", delay=0.15,
                                  where={"instance": "server_0"}):
                with failpoints.armed("server.execute.before", delay=0.35,
                                      where={"instance": "server_2"}):
                    resp = cluster.query(
                        "SELECT COUNT(*), SUM(v) FROM ph")
            assert not resp.exceptions, resp.exceptions
            assert resp.rows == truth.rows
        finally:
            cluster.stop()

    def test_primary_death_after_split_retries_unanswered_only(
            self, tmp_path):
        """Primary dies mid-query: the retry path re-scatters only the
        segments no hedge child answered, and the result is complete."""
        cluster = self._partial_cluster(tmp_path)
        try:
            truth = cluster.query("SELECT COUNT(*), SUM(v) FROM ph")
            cluster.routing.get_route("ph")._rr = 0  # primary = server_0
            # broker-side transport death (the site a SIGKILLed server
            # hits): server.execute.before would be caught server-side
            # and come back as a typed error payload instead
            with failpoints.armed(
                    "broker.scatter.before",
                    error=ConnectionError("chaos: primary died"),
                    where={"server": "server_0"}):
                resp = cluster.query("SELECT COUNT(*), SUM(v) FROM ph")
            assert not resp.exceptions, resp.exceptions
            assert resp.rows == truth.rows
        finally:
            cluster.stop()


# ---------------------------------------------------------------------------
# tier-1 smoke of the acceptance driver
# ---------------------------------------------------------------------------

class TestGroupsBenchSmoke:
    def test_groups_bench_smoke(self, tmp_path):
        """The --groups acceptance scenario at smoke scale: 2 groups,
        8-client closed loop, whole-group kill, zero failed queries,
        same-seed chaos journal replay — wired into tier-1. Writes its
        report to a temp path so the committed full-run
        BENCH_groups.json artifact is never clobbered by CI."""
        import importlib
        import json
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench = importlib.import_module("bench")
        out = str(tmp_path / "BENCH_groups_smoke.json")
        bench.groups_main(smoke=True, out_path=out)
        with open(out) as f:
            report = json.load(f)
        assert report["value"] == 0
        assert report["chaos_replay_identical"] is True
