"""HBM segment residency (ISSUE 6): the per-(segment, column)
device-resident tier with frequency-based admission (ops/residency.py).

Pins the tentpole properties deterministically:

  * cross-batch residency — a different pruned subset (or a batch that
    gained a segment) re-ships ONLY rows the device has never seen; the
    kernel-ready [S, D] block assembles on-device (the column transfer
    odometer is the witness)
  * admission — a cold one-pass scan cannot flush the hot working set;
    warmup-seeded rows bypass the frequency duel
  * invalidation — the segment-replace path drops the old version's
    resident rows while sparing the just-warmed live object's; a
    same-name/new-object segment can NEVER serve a stale block
  * warmup — SegmentWarmup replay stages the hot plans' columns into
    HBM (seeded) before the segment serves, including on an L2
    result-cache hit
  * params-cache bounding — a batch's predicate params evict with its
    last resident block instead of stranding until global LRU pressure
  * chaos — seeded segment replacement mid-traffic never serves a stale
    block and converges to the no-chaos run's results
"""
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops import residency as residency_mod
from pinot_tpu.ops.engine import TpuOperatorExecutor, _batch_id
from pinot_tpu.ops.residency import ResidencyManager
from pinot_tpu.query.context import QueryContext
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import failpoints

SQL = "SELECT SUM(m), COUNT(*) FROM t WHERE d < 5"


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def make_schema():
    return Schema("t", [
        FieldSpec("d", DataType.INT, FieldType.DIMENSION),
        FieldSpec("m", DataType.INT, FieldType.METRIC)])


def make_creator():
    tc = TableConfig("t", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["m"]
    return SegmentCreator(tc, make_schema())


def build_seg(tmp_path, name, n=4000, seed=11, m_value=None):
    rng = np.random.default_rng(seed)
    m = (np.full(n, m_value, dtype=np.int32) if m_value is not None
         else rng.integers(0, 100, n).astype(np.int32))
    p = str(tmp_path / f"{name}_{seed}_{m_value}")
    make_creator().build(
        {"d": rng.integers(0, 10, n).astype(np.int32), "m": m}, p, name)
    return load_segment(p)


@pytest.fixture()
def segs(tmp_path):
    return [build_seg(tmp_path, f"t_{i}", seed=11 + i) for i in range(3)]


def make_engine(**overrides):
    return TpuOperatorExecutor(config=PinotConfiguration(overrides=overrides))


def agg_values(results):
    return tuple(tuple(float(v) for v in r.intermediates) for r in results)


# ---------------------------------------------------------------------------
# ResidencyManager policy unit tests (no device work)
# ---------------------------------------------------------------------------

def _seg(name):
    return SimpleNamespace(name=name)


class TestAdmissionPolicy:
    def test_cold_scan_cannot_flush_hot_set(self):
        rm = ResidencyManager(300, admission=True, sample_window=10_000)
        hot = [_seg(f"h{i}") for i in range(3)]
        for s in hot:
            assert rm.get(s, "ids", "c", "i1") is None
            assert rm.admit(s, "ids", "c", "i1", object(), 100)
        for _ in range(5):  # build the working set's frequency
            for s in hot:
                assert rm.get(s, "ids", "c", "i1") is not None
        for i in range(5):  # one cold pass over another table
            c = _seg(f"cold{i}")
            rm.get(c, "ids", "c", "i1")
            assert not rm.admit(c, "ids", "c", "i1", object(), 100)
        assert rm.rejected == 5
        for s in hot:  # working set survived intact
            assert rm.get(s, "ids", "c", "i1") is not None

    def test_repeated_traffic_earns_admission(self):
        """A genuinely hot newcomer accrues frequency across its misses
        and eventually wins the duel against a colder victim."""
        rm = ResidencyManager(200, admission=True, sample_window=10_000)
        a, b = _seg("a"), _seg("b")
        for s in (a, b):
            rm.get(s, "ids", "c", "i1")
            assert rm.admit(s, "ids", "c", "i1", object(), 100)
        new = _seg("new")
        for _ in range(3):  # misses still count toward admission credit
            rm.get(new, "ids", "c", "i1")
        rm.get(new, "ids", "c", "i1")
        assert rm.admit(new, "ids", "c", "i1", object(), 100)
        assert rm.evicted == 1  # displaced the coldest resident

    def test_seeded_admission_bypasses_duel(self):
        rm = ResidencyManager(200, admission=True, sample_window=10_000)
        for name in ("a", "b"):
            s = _seg(name)
            for _ in range(10):
                rm.get(s, "ids", "c", "i1")
            rm.admit(s, "ids", "c", "i1", object(), 100)
        warm = _seg("warm")
        with rm.seeding():
            rm.get(warm, "ids", "c", "i1")
            assert rm.admit(warm, "ids", "c", "i1", object(), 100)
        assert rm.get(warm, "ids", "c", "i1") is not None

    def test_frequency_ages_out(self):
        rm = ResidencyManager(1000, admission=True, sample_window=64)
        s = _seg("s")
        for _ in range(40):
            rm.get(s, "ids", "c", "i1")
        peak = rm.frequency("s", "ids", "c")
        for i in range(40):  # unrelated traffic fills the sample window
            rm.get(_seg(f"o{i}"), "ids", "c", "i1")
        assert rm.frequency("s", "ids", "c") < peak

    def test_invalidate_spares_live_object(self):
        rm = ResidencyManager(1000)
        old, new = _seg("x"), _seg("x")
        rm.admit(old, "ids", "c", "i1", object(), 10)
        rm.admit(new, "ids", "c", "i1", object(), 10)
        assert rm.invalidate_segment("x", keep=new) == 1
        assert rm.get(new, "ids", "c", "i1") is not None
        assert rm.get(old, "ids", "c", "i1") is None


# ---------------------------------------------------------------------------
# Cross-batch residency through the engine
# ---------------------------------------------------------------------------

class TestCrossBatchResidency:
    def test_changed_batch_ships_zero_column_bytes(self, segs):
        """THE tentpole property: a different pruned subset of already-
        resident segments assembles its [S, D] blocks on-device — zero
        bytes cross the host->device link for columns."""
        eng = make_engine()
        ctx = QueryContext.from_sql(SQL)
        res, rem = eng.execute(segs, ctx)
        assert not rem
        want_sub = agg_values(make_engine().execute(segs[:2], ctx)[0])
        c0 = residency_mod.column_transfer_bytes()
        res2, rem2 = eng.execute(segs[:2], ctx)  # different composition
        assert not rem2
        assert residency_mod.column_transfer_bytes() == c0, \
            "resident rows were re-shipped for a recomposed batch"
        assert agg_values(res2) == want_sub  # on-device assembly is exact

    def test_new_segment_uploads_only_its_rows(self, segs):
        eng = make_engine()
        ctx = QueryContext.from_sql(SQL)
        start = residency_mod.column_transfer_bytes()
        eng.execute(segs[:2], ctx)
        two_segments = residency_mod.column_transfer_bytes() - start
        assert two_segments > 0
        c0 = residency_mod.column_transfer_bytes()
        m0 = eng._residency.misses
        res, rem = eng.execute(segs, ctx)  # one NEW segment joins
        assert not rem
        delta = residency_mod.column_transfer_bytes() - c0
        assert 0 < delta < two_segments  # only the newcomer's rows
        # exactly the new segment's two rows (ids:d + val:m) missed
        assert eng._residency.misses - m0 == 2
        assert agg_values(res) == agg_values(make_engine().execute(
            segs, ctx)[0])

    def test_hist_slot_params_cached_zero_steady_transfers(self, segs):
        """Histogram/tdigest slots carry per-batch bucket bounds; they
        ride the params cache like leaf params, so a repeated sketch
        query uploads nothing at all."""
        from pinot_tpu.query.executor import QueryExecutor
        eng = make_engine()
        ex = QueryExecutor(segs, use_tpu=True, engine=eng)
        sql = "SELECT PERCENTILETDIGEST95(m), COUNT(*) FROM t"
        r1 = ex.execute(sql)
        assert eng._block_cache, "sketch query fell back to host"
        b0 = residency_mod.transfer_bytes()
        r2 = ex.execute(sql)
        assert residency_mod.transfer_bytes() == b0, \
            "repeated hist query re-uploaded slot params"
        assert r2.rows == r1.rows

    def test_group_by_blocks_ride_residency(self, segs):
        eng = make_engine()
        ctx = QueryContext.from_sql(
            "SELECT d, SUM(m) FROM t GROUP BY d")
        eng.execute(segs, ctx)
        c0 = residency_mod.column_transfer_bytes()
        res, rem = eng.execute(segs[:2], ctx)
        assert not rem
        assert residency_mod.column_transfer_bytes() == c0
        want = make_engine().execute(segs[:2], ctx)[0]
        got = {k: tuple(float(x) for x in v)
               for r in res for k, v in r.groups.items()}
        expect = {k: tuple(float(x) for x in v)
                  for r in want for k, v in r.groups.items()}
        assert got == expect


# ---------------------------------------------------------------------------
# Invalidation / identity
# ---------------------------------------------------------------------------

class TestInvalidation:
    def test_same_name_new_object_never_serves_stale(self, tmp_path):
        eng = make_engine()
        ctx = QueryContext.from_sql("SELECT SUM(m), COUNT(*) FROM t")
        v1 = build_seg(tmp_path, "t_0", n=500, m_value=1)
        v2 = build_seg(tmp_path, "t_0", n=500, m_value=2)
        r1, _ = eng.execute([v1], ctx)
        assert agg_values(r1) == ((500.0, 500.0),)
        r2, _ = eng.execute([v2], ctx)  # same name, new object
        assert agg_values(r2) == ((1000.0, 500.0),)
        r1b, _ = eng.execute([v1], ctx)  # and back — still exact
        assert agg_values(r1b) == ((500.0, 500.0),)

    def test_invalidate_segment_drops_every_tier(self, segs):
        eng = make_engine()
        ctx = QueryContext.from_sql(SQL)
        eng.execute(segs, ctx)
        name = segs[0].name
        assert eng._residency.resident_for(name) > 0
        eng.invalidate_segment(name)
        assert eng._residency.resident_for(name) == 0
        assert not any(any(s.name == name for s in e[0])
                       for e in eng._block_cache.values())
        assert not any(any(s.name == name for s in v[0])
                       for v in eng._params_cache.values())
        assert not any(v[0].name == name for v in eng._host_rows.values())
        res, rem = eng.execute(segs, ctx)  # re-stages cleanly
        assert not rem and res

    def test_replace_event_swaps_residency_to_live_object(self, tmp_path):
        """Through the REAL server path: a same-name segment replace
        drops the old version's resident rows via the segment-event
        hook, warmup re-stages the new version (seeded) BEFORE it
        serves, and answers flip to the new data."""
        from pinot_tpu.server.data_manager import InstanceDataManager
        from pinot_tpu.server.datatable import deserialize_results
        from pinot_tpu.server.query_server import ServerQueryExecutor
        v1 = build_seg(tmp_path, "t_0", n=500, m_value=1)
        v2 = build_seg(tmp_path, "t_0", n=500, m_value=2)
        dm = InstanceDataManager("srv0")
        ex = ServerQueryExecutor(dm, use_tpu=True,
                                 config=PinotConfiguration())
        sql = "SELECT SUM(m), COUNT(*) FROM t"
        try:
            dm.table("t_OFFLINE").add_segment(v1)
            results, _exc, _st = deserialize_results(
                ex.execute("t_OFFLINE", sql))
            assert float(results[0].intermediates[0]) == 500.0
            eng = ex._shared_engine()
            assert eng._residency.resident_for("t_0") > 0
            dm.table("t_OFFLINE").add_segment(v2)  # replace
            with eng._engine_lock:
                pinned = [e[0] for k, e in
                          eng._residency._entries.items() if k[1] == "t_0"]
            # warmup re-staged the NEW object; the old one is gone
            assert pinned and all(p is v2 for p in pinned)
            results, _exc, _st = deserialize_results(
                ex.execute("t_OFFLINE", sql + " OPTION(skipCache=true)"))
            assert float(results[0].intermediates[0]) == 1000.0
        finally:
            dm.shutdown()
            ex.segment_cache.close()
            ex.fingerprint_log.close()


# ---------------------------------------------------------------------------
# Warmup -> proactive residency
# ---------------------------------------------------------------------------

class TestWarmupSeeding:
    def test_warm_stages_columns_seeded(self, segs):
        from pinot_tpu.cache.segment_cache import SegmentResultCache
        from pinot_tpu.cache.warmup import FingerprintLog, SegmentWarmup
        eng = make_engine()
        log = FingerprintLog()
        ctx = QueryContext.from_sql(SQL)
        log.record("t", ctx.fingerprint(), SQL)
        cache = SegmentResultCache()
        w = SegmentWarmup(log, cache, use_tpu=True, engine_fn=lambda: eng)
        assert w.warm("t", segs[0]) >= 1
        name = segs[0].name
        assert eng._residency.resident_for(name) > 0
        # seeded: one replay left MORE than one access worth of credit
        assert eng._residency.frequency(name, "val", "m") > 1
        # L2-hit path still prestages: drop the device tier, warm again —
        # the result cache hits, but columns come back resident anyway
        eng.drop_caches()
        assert eng._residency.resident_for(name) == 0
        assert w.warm("t", segs[0]) >= 1
        assert eng._residency.resident_for(name) > 0


# ---------------------------------------------------------------------------
# Params-cache bounding (satellite)
# ---------------------------------------------------------------------------

class TestParamsCacheBounded:
    def test_params_evict_with_last_block(self, segs):
        # budget fits ONE batch's blocks (~295KB each), so staging batch
        # B evicts batch A's blocks — and with them A's params entries
        eng = make_engine(**{"pinot.server.hbm.cache.bytes": 500_000})
        ctx = QueryContext.from_sql(SQL)
        eng.execute(segs[:2], ctx)
        key_a = _batch_id(segs[:2])
        assert any(k[0] == key_a for k in eng._params_cache)
        eng.execute(segs, ctx)
        assert not any(k[0] == key_a for k in eng._block_cache), \
            "test premise: batch A's blocks should have evicted"
        assert not any(k[0] == key_a for k in eng._params_cache), \
            "params for a fully evicted batch were stranded"

    def test_invalidate_drops_params_for_segment(self, segs):
        eng = make_engine()
        ctx = QueryContext.from_sql(SQL)
        eng.execute(segs, ctx)
        assert eng._params_cache
        eng.invalidate_segment(segs[1].name)
        assert not any(any(s.name == segs[1].name for s in v[0])
                       for v in eng._params_cache.values())


# ---------------------------------------------------------------------------
# Chaos: segment replacement mid-traffic (acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestResidencyChaos:
    SQL = "SELECT SUM(m), COUNT(*) FROM rt OPTION(skipCache=true)"

    def _run(self, tmp_path, tag, chaos=None):
        from pinot_tpu.cluster.mini import MiniCluster
        (tmp_path / tag).mkdir(exist_ok=True)
        v1 = build_seg(tmp_path / tag, "rt_0", n=400, m_value=1)
        v2 = build_seg(tmp_path / tag, "rt_0", n=400, m_value=2)
        c = MiniCluster(num_servers=1, use_tpu=True, chaos=chaos)
        c.start()
        try:
            c.add_table("rt")
            c.add_segment("rt", v1, server_idx=0)
            seen = []
            errors = []
            stop = threading.Event()

            def traffic():
                while not stop.is_set():
                    r = c.query(self.SQL)
                    if r.exceptions:
                        errors.append(r.exceptions)
                    elif r.rows:
                        seen.append(tuple(float(x) for x in r.rows[0]))

            threads = [threading.Thread(target=traffic) for _ in range(3)]
            for t in threads:
                t.start()
            time.sleep(0.3)
            c.add_segment("rt", v2, server_idx=0)  # replace mid-traffic
            time.sleep(0.3)
            stop.set()
            for t in threads:
                t.join()
            final = tuple(float(x) for x in c.query(self.SQL).rows[0])
            eng = c.servers[0].executor._engine
            pinned = []
            if eng is not None:
                with eng._engine_lock:
                    pinned = [e[0] for k, e in
                              eng._residency._entries.items()
                              if k[1] == "rt_0"]
            return {"seen": set(seen), "errors": errors, "final": final,
                    "stale_pins": [p for p in pinned if p is not v2]}
        finally:
            c.stop()

    def test_replace_mid_traffic_never_serves_stale(self, tmp_path):
        """ISSUE 6 acceptance: seeded chaos delaying execution around a
        same-name segment replace — every observed answer is exactly the
        old or the new version's (a stale resident block would produce
        either a wrong value or a torn mix), the final state converges
        to the no-chaos run's, and no stale object stays pinned."""
        v1_rows, v2_rows = (400.0, 400.0), (800.0, 400.0)
        baseline = self._run(tmp_path, "nochaos", chaos=None)
        assert baseline["final"] == v2_rows
        assert not baseline["errors"]
        assert baseline["seen"] <= {v1_rows, v2_rows}

        chaos = [
            ("server.execute.before",
             {"delay": 0.01, "probability": 0.5, "seed": 1234}),
            ("server.execute.segment",
             {"delay": 0.005, "probability": 0.5, "seed": 99}),
        ]
        run = self._run(tmp_path, "chaos", chaos=chaos)
        assert not run["errors"]
        assert run["seen"], "traffic never completed a query"
        assert run["seen"] <= {v1_rows, v2_rows}, \
            f"stale/torn answers observed: {run['seen']}"
        assert run["final"] == baseline["final"] == v2_rows
        assert not run["stale_pins"]
