"""Two-tier query result cache (pinot_tpu/cache/): broker whole-result
cache + server per-segment partial cache with version-based invalidation.

Covers the hard part explicitly: correctness under mutation — queries
racing segment replace and realtime appends must never see stale reads,
and on a hybrid table only the mutable tail re-executes.
"""
import threading
import time

import numpy as np
import pytest

from pinot_tpu.cache import (BrokerResultCache, LruTtlCache,
                             SegmentResultCache, segment_version)
from pinot_tpu.cache.segment_cache import (is_cacheable_segment,
                                           is_cacheable_shape)
from pinot_tpu.cluster.mini import MiniCluster
from pinot_tpu.ingest.mutable_segment import MutableSegment
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.server.data_manager import InstanceDataManager, TableDataManager


def _schema():
    return Schema.from_dict({
        "schemaName": "t",
        "dimensionFieldSpecs": [{"name": "d", "dataType": "LONG"}],
        "metricFieldSpecs": [{"name": "m", "dataType": "LONG"}]})


def _table_config():
    return TableConfig.from_dict({"tableName": "t", "tableType": "OFFLINE"})


def _build(tmp_path, name, d, m):
    out = str(tmp_path / name)
    SegmentCreator(_table_config(), _schema()).build(
        {"d": np.asarray(d, np.int64), "m": np.asarray(m, np.int64)},
        out, name)
    return load_segment(out)


# ---------------------------------------------------------------------------
class TestFingerprint:
    def test_stable_and_canonical(self):
        sql = "SELECT SUM(m), d FROM t WHERE d > 3 GROUP BY d LIMIT 7"
        a = QueryContext.from_sql(sql).fingerprint()
        b = QueryContext.from_sql(sql).fingerprint()
        assert a == b

    def test_cache_options_do_not_change_fingerprint(self):
        base = QueryContext.from_sql("SELECT COUNT(*) FROM t")
        skip = QueryContext.from_sql(
            "SELECT COUNT(*) FROM t OPTION(skipCache=true)")
        trace = QueryContext.from_sql(
            "SELECT COUNT(*) FROM t OPTION(trace=true)")
        assert base.fingerprint() == skip.fingerprint() == trace.fingerprint()

    def test_result_affecting_parts_change_fingerprint(self):
        fps = {QueryContext.from_sql(sql).fingerprint() for sql in [
            "SELECT COUNT(*) FROM t",
            "SELECT COUNT(*) FROM t2",
            "SELECT COUNT(*) FROM t WHERE d = 1",
            "SELECT COUNT(*) FROM t GROUP BY d",
            "SELECT COUNT(*) FROM t LIMIT 5",
            "SELECT COUNT(*) FROM t OPTION(numGroupsLimit=10)",
            "SELECT DISTINCT d FROM t",
        ]}
        assert len(fps) == 7


class TestLruTtlCache:
    def test_lru_eviction_respects_recency(self):
        c = LruTtlCache(max_bytes=10, ttl_seconds=60)
        c.put("a", b"xxxx")
        c.put("b", b"yyyy")
        assert c.get("a") == b"xxxx"     # refresh a
        c.put("c", b"zzzz")              # over budget: evicts b, not a
        assert c.get("b") is None
        assert c.get("a") == b"xxxx"
        assert c.stats.evictions == 1

    def test_ttl_expiry(self):
        t = [0.0]
        c = LruTtlCache(max_bytes=100, ttl_seconds=5, clock=lambda: t[0])
        c.put("k", b"v")
        assert c.get("k") == b"v"
        t[0] = 5.1
        assert c.get("k") is None
        assert c.stats.expirations == 1

    def test_oversized_payload_refused(self):
        c = LruTtlCache(max_bytes=4, ttl_seconds=60)
        assert not c.put("k", b"12345")
        assert len(c) == 0

    def test_invalidate_predicate(self):
        c = LruTtlCache(max_bytes=100, ttl_seconds=60)
        c.put(("seg_0", 1), b"a")
        c.put(("seg_1", 1), b"b")
        assert c.invalidate(lambda k: k[0] == "seg_0") == 1
        assert c.get(("seg_0", 1)) is None
        assert c.get(("seg_1", 1)) == b"b"


# ---------------------------------------------------------------------------
class TestSegmentCacheTier2:
    def test_cacheability(self, tmp_path):
        imm = _build(tmp_path, "imm", [1, 2], [1, 2])
        mut = MutableSegment("t__0__0__1", TableConfig("t", TableType.REALTIME),
                             _schema())
        assert is_cacheable_segment(imm)
        assert not is_cacheable_segment(mut)
        # upsert segments (live validity bitmap) must not be cached
        imm.valid_doc_ids = object()
        assert not is_cacheable_segment(imm)
        agg = QueryContext.from_sql("SELECT SUM(m) FROM t")
        sel = QueryContext.from_sql("SELECT d FROM t LIMIT 5")
        assert is_cacheable_shape(agg)
        assert not is_cacheable_shape(sel)

    def test_segment_version_prefers_crc(self, tmp_path):
        a = _build(tmp_path, "va", [1, 2, 3], [1, 1, 1])
        b = load_segment(str(tmp_path / "va"))
        assert a.metadata.crc != 0
        assert segment_version(a) == segment_version(b)  # same content
        c = _build(tmp_path, "vc", [1, 2, 3], [2, 2, 2])
        assert segment_version(a) != segment_version(c)

    def test_repeat_query_hits_and_matches(self, tmp_path):
        segs = [_build(tmp_path, f"s{i}", range(100), [i + 1] * 100)
                for i in range(3)]
        cache = SegmentResultCache()
        sql = "SELECT COUNT(*), SUM(m) FROM t WHERE d < 50"
        cold = QueryExecutor(segs, use_tpu=False,
                             segment_cache=cache).execute(sql)
        assert cache.stats.puts == 3 and cache.stats.hits == 0
        warm = QueryExecutor(segs, use_tpu=False,
                             segment_cache=cache).execute(sql)
        assert cache.stats.hits == 3
        assert warm.result_table.rows == cold.result_table.rows

    def test_group_by_and_distinct_hit(self, tmp_path):
        segs = [_build(tmp_path, f"g{i}", [j % 4 for j in range(80)],
                       range(80)) for i in range(2)]
        cache = SegmentResultCache()
        for sql in ("SELECT d, SUM(m) FROM t GROUP BY d ORDER BY d LIMIT 10",
                    "SELECT DISTINCT d FROM t LIMIT 10"):
            first = QueryExecutor(segs, use_tpu=False,
                                  segment_cache=cache).execute(sql)
            hits0 = cache.stats.hits
            second = QueryExecutor(segs, use_tpu=False,
                                   segment_cache=cache).execute(sql)
            assert cache.stats.hits == hits0 + 2
            assert second.result_table.rows == first.result_table.rows

    def test_mutable_segment_never_cached(self):
        mut = MutableSegment("t__0__0__1",
                             TableConfig("t", TableType.REALTIME), _schema())
        for i in range(10):
            mut.index({"d": i, "m": 1})
        cache = SegmentResultCache()
        sql = "SELECT COUNT(*) FROM t"
        r = QueryExecutor([mut], use_tpu=False,
                          segment_cache=cache).execute(sql)
        assert r.rows[0][0] == 10
        assert len(cache) == 0
        # appended rows are visible on the very next query
        mut.index({"d": 10, "m": 1})
        r = QueryExecutor([mut], use_tpu=False,
                          segment_cache=cache).execute(sql)
        assert r.rows[0][0] == 11
        assert cache.stats.hits == 0

    def test_replace_invalidates_by_version(self, tmp_path):
        seg_v1 = _build(tmp_path, "r1", [1, 2, 3], [1, 1, 1])
        cache = SegmentResultCache()
        sql = "SELECT SUM(m) FROM t"
        r = QueryExecutor([seg_v1], use_tpu=False,
                          segment_cache=cache).execute(sql)
        assert r.rows[0][0] == 3
        # same name, new content -> new crc -> the cached partial is
        # unreachable, NOT stale-served
        out = str(tmp_path / "r1b")
        SegmentCreator(_table_config(), _schema()).build(
            {"d": np.asarray([1, 2, 3], np.int64),
             "m": np.asarray([5, 5, 5], np.int64)}, out, "r1")
        seg_v2 = load_segment(out)
        assert seg_v2.name == seg_v1.name
        r = QueryExecutor([seg_v2], use_tpu=False,
                          segment_cache=cache).execute(sql)
        assert r.rows[0][0] == 15

    def test_cached_partial_is_a_private_copy(self, tmp_path):
        """Reduce mutates result containers in place; a hit must hand out
        a fresh copy, not the stored object."""
        seg = _build(tmp_path, "p1", [0, 1] * 10, range(20))
        cache = SegmentResultCache()
        sql = "SELECT d, SUM(m) FROM t GROUP BY d ORDER BY d LIMIT 10"
        a = QueryExecutor([seg], use_tpu=False,
                          segment_cache=cache).execute(sql)
        b = QueryExecutor([seg], use_tpu=False,
                          segment_cache=cache).execute(sql)
        c = QueryExecutor([seg], use_tpu=False,
                          segment_cache=cache).execute(sql)
        assert a.result_table.rows == b.result_table.rows == c.result_table.rows

    def test_trace_carries_cache_hit_attr(self, tmp_path):
        seg = _build(tmp_path, "tr1", range(10), range(10))
        cache = SegmentResultCache()
        sql = "SELECT SUM(m) FROM t OPTION(trace=true)"
        QueryExecutor([seg], use_tpu=False, segment_cache=cache).execute(sql)
        r = QueryExecutor([seg], use_tpu=False,
                          segment_cache=cache).execute(sql)
        assert r.trace is not None
        assert r.trace.get("cacheHit") is True
        flat = str(r.trace)
        assert "SegmentResultCache" in flat

    def test_data_manager_hook_invalidates(self, tmp_path):
        idm = InstanceDataManager("s0")
        events = []
        idm.add_segment_listener(lambda *a: events.append(a))
        tdm = idm.table("t_OFFLINE")
        v0 = tdm.version
        seg = _build(tmp_path, "h1", [1], [1])
        tdm.add_segment(seg)
        assert tdm.version == v0 + 1
        assert events[-1] == ("add", "t_OFFLINE", "h1")
        tdm.add_segment(_build(tmp_path, "h1b", [1], [2]))
        tdm.add_segment(load_segment(str(tmp_path / "h1")))  # replace h1
        assert events[-1] == ("replace", "t_OFFLINE", "h1")
        tdm.remove_segment("h1")
        assert events[-1] == ("remove", "t_OFFLINE", "h1")
        assert tdm.version == v0 + 4


# ---------------------------------------------------------------------------
class TestBrokerCacheTier1:
    @pytest.fixture()
    def cluster(self, tmp_path):
        c = MiniCluster(num_servers=2, result_cache=True)
        c.start()
        c.add_table("t")
        for i in range(4):
            seg = _build(tmp_path, f"b{i}", range(100), [i] * 100)
            c.add_segment("t", seg, server_idx=i % 2)
        yield c, tmp_path
        c.stop()

    def test_repeat_query_served_from_cache(self, cluster):
        c, _ = cluster
        sql = "SELECT COUNT(*), SUM(m) FROM t WHERE d < 50"
        cold = c.query(sql)
        assert not cold.exceptions and not cold.cache_hit
        warm = c.query(sql)
        assert warm.cache_hit
        assert warm.result_table.rows == cold.result_table.rows
        assert c.broker.result_cache.stats.hits >= 1

    def test_skip_cache_option_bypasses(self, cluster):
        c, _ = cluster
        sql = "SELECT COUNT(*) FROM t"
        c.query(sql)
        assert not c.query(sql + " OPTION(skipCache=true)").cache_hit
        assert not c.query(sql + " OPTION(useCache=false)").cache_hit
        assert c.query(sql).cache_hit

    def test_segment_add_and_remove_invalidate(self, cluster):
        c, tmp_path = cluster
        sql = "SELECT COUNT(*) FROM t"
        assert c.query(sql).rows[0][0] == 400
        assert c.query(sql).cache_hit
        seg = _build(tmp_path, "extra", range(10), [9] * 10)
        c.add_segment("t", seg, server_idx=0)
        r = c.query(sql)  # epoch moved: recomputed, fresh count
        assert not r.cache_hit
        assert r.rows[0][0] == 410
        c.remove_segment("t", "extra")
        # back to the ORIGINAL segment set: the original epoch's entry is
        # addressable again and is still correct (content-hash epochs are
        # set-addressed, not event-ordered) — the answer must be 400
        # either way, never the 410 of the removed-segment era
        assert c.query(sql).rows[0][0] == 400

    def test_segment_replace_invalidates(self, cluster):
        c, tmp_path = cluster
        sql = "SELECT SUM(m) FROM t"
        before = c.query(sql).rows[0][0]
        assert c.query(sql).cache_hit
        # rebuild b0 (same name, new values) and swap it in
        out = str(tmp_path / "b0v2")
        SegmentCreator(_table_config(), _schema()).build(
            {"d": np.arange(100, dtype=np.int64),
             "m": np.full(100, 100, np.int64)}, out, "b0")
        c.add_segment("t", load_segment(out), server_idx=0)
        r = c.query(sql)
        assert not r.cache_hit
        assert r.rows[0][0] == before + 100 * 100  # b0 had m=0

    def test_realtime_table_not_cached(self, tmp_path):
        c = MiniCluster(num_servers=1, result_cache=True)
        c.start()
        try:
            c.add_table("t", table_type="REALTIME")
            seg = _build(tmp_path, "rt0", range(10), [1] * 10)
            c.add_segment("t", seg, server_idx=0, table_type="REALTIME")
            sql = "SELECT COUNT(*) FROM t"
            assert c.query(sql).rows[0][0] == 10
            r = c.query(sql)
            assert not r.cache_hit  # consuming side: whole-result unsafe
        finally:
            c.stop()

    def test_partial_responses_not_cached(self, tmp_path):
        c = MiniCluster(num_servers=2, result_cache=True)
        c.start()
        try:
            c.add_table("t")
            c.add_segment("t", _build(tmp_path, "pr0", range(10), [1] * 10),
                          server_idx=0)
            c.add_segment("t", _build(tmp_path, "pr1", range(10), [1] * 10),
                          server_idx=1)
            c.servers[1].transport.stop()
            c._connections["server_1"].close()
            sql = "SELECT COUNT(*) FROM t"
            r = c.query(sql)
            assert r.exceptions  # unreplicated segment lost
            r = c.query(sql)
            assert not r.cache_hit  # the partial answer was NOT memoized
        finally:
            c.stop()


# ---------------------------------------------------------------------------
class TestMutationRaces:
    """Satellite: queries racing segment replace + realtime appends on a
    hybrid segment set — no stale reads, mutable tail always re-executes."""

    @pytest.mark.slow
    def test_threaded_no_stale_reads(self, tmp_path):
        self._run_race(tmp_path)

    def test_threaded_no_stale_reads_quick(self, tmp_path):
        self._run_race(tmp_path, appends=60, duration_s=2.0)

    def _run_race(self, tmp_path, appends=300, duration_s=8.0):
        idm = InstanceDataManager("s0")
        tdm = idm.table("t_REALTIME")
        cache = SegmentResultCache(metrics=None)
        # immutable bulk: 2 sealed segments (SUM(m) = 2 * 1000)
        for i in range(2):
            tdm.add_segment(_build(tmp_path, f"race_imm{i}",
                                   range(1000), [1] * 1000))
        mut = MutableSegment("t__0__0__1",
                             TableConfig("t", TableType.REALTIME), _schema())
        tdm.add_segment(mut)

        # replace thread: rebuild race_imm0 with the SAME totals but new
        # crc, over and over — version keying must keep answers exact
        stop = threading.Event()
        replace_errs = []

        def replacer():
            n = 0
            try:
                while not stop.is_set():
                    n += 1
                    out = str(tmp_path / f"race_imm0_v{n}")
                    SegmentCreator(_table_config(), _schema()).build(
                        {"d": np.arange(1000, dtype=np.int64) + n,
                         "m": np.ones(1000, np.int64)}, out, "race_imm0")
                    tdm.add_segment(load_segment(out))
            except Exception as e:  # noqa: BLE001
                replace_errs.append(e)

        t = threading.Thread(target=replacer, daemon=True)
        t.start()
        sql = "SELECT COUNT(*), SUM(m) FROM t"
        deadline = time.time() + duration_s
        try:
            for i in range(appends):
                mut.index({"d": 10_000 + i, "m": 1})
                sdms = tdm.acquire_segments()
                try:
                    r = QueryExecutor([s.segment for s in sdms],
                                      use_tpu=False,
                                      segment_cache=cache).execute(sql)
                finally:
                    TableDataManager.release_all(sdms)
                expect = 2000 + i + 1
                # the row ingested right before this query MUST be visible
                assert r.rows[0][0] == expect, (i, r.rows)
                assert r.rows[0][1] == expect
                if time.time() > deadline:
                    break
        finally:
            stop.set()
            t.join(timeout=10)
        assert not replace_errs
        # the immutable bulk was served from cache (mutable tail was not):
        # every query re-executed at most the mutable segment + the
        # freshly replaced immutable
        assert cache.stats.hits > 0
        assert cache.stats.misses > 0


# ---------------------------------------------------------------------------
class TestBrokerCacheUnit:
    def _resp(self, queried=1, responded=1, exceptions=()):
        from pinot_tpu.query.reduce import BrokerResponse, ResultTable
        r = BrokerResponse(result_table=ResultTable(["c"], ["LONG"], [(1,)]))
        r.num_servers_queried = queried
        r.num_servers_responded = responded
        r.exceptions = list(exceptions)
        return r

    def test_put_get_roundtrip_copies(self):
        c = BrokerResultCache()
        assert c.put("fp", "t", "e", self._resp())
        hit = c.get("fp", "t", "e")
        assert hit is not None and hit.rows == [(1,)]
        hit.result_table.rows.append((2,))  # caller mutation must not leak
        assert c.get("fp", "t", "e").rows == [(1,)]

    def test_incomplete_or_errored_not_cached(self):
        c = BrokerResultCache()
        assert not c.put("f", "t", "e", self._resp(
            exceptions=[{"errorCode": 427, "message": "x"}]))
        assert not c.put("f", "t", "e", self._resp(queried=2, responded=1))

    def test_epoch_changes_key(self):
        c = BrokerResultCache()
        c.put("fp", "t", "epoch1", self._resp())
        assert c.get("fp", "t", "epoch2") is None

    def test_invalidate_table(self):
        c = BrokerResultCache()
        c.put("f1", "t", "e", self._resp())
        c.put("f2", "u", "e", self._resp())
        assert c.invalidate_table("t") == 1
        assert c.get("f1", "t", "e") is None
        assert c.get("f2", "u", "e") is not None


class TestRoutingEpoch:
    def test_epoch_moves_on_segment_changes(self):
        from pinot_tpu.broker.routing import (RoutingTable, SegmentInfo,
                                              TableRoute)
        tr = TableRoute("t_OFFLINE")
        rt = RoutingTable(offline=tr)
        e0 = rt.epoch()
        tr.segments["s0"] = SegmentInfo("s0", ["srv0"], version=111)
        e1 = rt.epoch()
        assert e1 != e0
        tr.segments["s0"] = SegmentInfo("s0", ["srv0"], version=222)
        e2 = rt.epoch()  # replace: version changed
        assert e2 != e1
        del tr.segments["s0"]
        assert rt.epoch() == e0
        # replica placement does NOT move the epoch
        tr.segments["s0"] = SegmentInfo("s0", ["srv0"], version=111)
        ea = rt.epoch()
        tr.segments["s0"] = SegmentInfo("s0", ["srv0", "srv1"], version=111)
        assert rt.epoch() == ea
        # time boundary DOES
        rt.time_boundary = 5
        assert rt.epoch() != ea


# ---------------------------------------------------------------------------
class TestMetricsSatellites:
    def test_type_emitted_once_per_name(self):
        from pinot_tpu.utils.metrics import MetricsRegistry
        m = MetricsRegistry("x")
        m.add_meter("q", labels={"table": "a"})
        m.add_meter("q", labels={"table": "b"})
        text = m.prometheus_text()
        assert text.count("# TYPE pinot_tpu_x_q counter") == 1

    def test_label_escaping(self):
        from pinot_tpu.utils.metrics import MetricsRegistry
        m = MetricsRegistry("x")
        m.add_meter("q", labels={"t": 'a"b\\c\nd'})
        text = m.prometheus_text()
        assert 't="a\\"b\\\\c\\nd"' in text

    def test_timer_quantiles(self):
        from pinot_tpu.utils.metrics import MetricsRegistry
        m = MetricsRegistry("x")
        for v in range(1, 101):
            m.add_timing("lat", float(v))
        t = m.timer("lat")
        assert t.quantile(0.5) == 50.0
        assert t.quantile(0.95) == 95.0
        assert t.quantile(0.99) == 99.0
        text = m.prometheus_text()
        assert 'pinot_tpu_x_lat{quantile="0.5"} 50' in text
        assert 'pinot_tpu_x_lat{quantile="0.99"} 99' in text

    def test_timer_reservoir_bounded(self):
        from pinot_tpu.utils.metrics import Timer
        t = Timer()
        for v in range(10_000):
            t.update(float(v))
        assert len(t._reservoir) == Timer.RESERVOIR_SIZE
        assert t.count == 10_000
        # reservoir holds a representative sample, not just the tail
        assert t.quantile(0.5) < 9_000


class TestEngineParamsCacheLru:
    def test_bounded_lru_shape(self):
        # structural check (no device work): the params cache is an
        # OrderedDict with a capacity constant, not an unbounded dict
        from collections import OrderedDict

        from pinot_tpu.ops.engine import TpuOperatorExecutor
        assert TpuOperatorExecutor.PARAMS_CACHE_ENTRIES == 4096
        ex = TpuOperatorExecutor.__new__(TpuOperatorExecutor)
        ex._params_cache = OrderedDict()
        assert isinstance(ex._params_cache, OrderedDict)
