"""Query schedulers: FCFS / token-priority fairness / binary workload.

Ref: pinot-core query/scheduler/ (FCFSQueryScheduler, PriorityScheduler +
token buckets, BinaryWorkloadScheduler) — SURVEY §2.5 schedulers row.
"""
import threading
import time

import pytest

from pinot_tpu.server.scheduler import (
    BinaryWorkloadScheduler, FCFSQueryScheduler, TokenPriorityScheduler,
    make_scheduler)


class TestFactory:
    def test_names(self):
        assert isinstance(make_scheduler("fcfs"), FCFSQueryScheduler)
        assert isinstance(make_scheduler("priority"), TokenPriorityScheduler)
        assert isinstance(make_scheduler("binary"), BinaryWorkloadScheduler)
        with pytest.raises(ValueError):
            make_scheduler("nope")


class TestFcfs:
    def test_runs_and_propagates(self):
        s = make_scheduler("fcfs", num_threads=2)
        try:
            assert s.submit(lambda: b"ok").result(5) == b"ok"
            fut = s.submit(lambda: (_ for _ in ()).throw(ValueError("x")))
            with pytest.raises(ValueError):
                fut.result(5)
        finally:
            s.stop()


class TestTokenPriority:
    def test_flooding_table_cannot_starve_light_one(self):
        """One worker; table A floods 20 slow queries, then table B sends
        2. B's queries must not wait behind A's whole backlog — A's spent
        tokens push its priority below B's."""
        s = TokenPriorityScheduler(num_threads=1, tokens_per_interval=10.0,
                                   interval_s=0.1)
        s.start()
        try:
            done = []

            def slow(tag):
                def run():
                    time.sleep(0.02)
                    done.append(tag)
                    return b""
                return run

            futs = [s.submit(slow(("A", i)), table="A") for i in range(20)]
            time.sleep(0.06)  # A starts burning tokens
            futs += [s.submit(slow(("B", i)), table="B") for i in range(2)]
            for f in futs:
                f.result(20)
            b_last = max(i for i, t in enumerate(done) if t[0] == "B")
            a_last = max(i for i, t in enumerate(done) if t[0] == "A")
            # B finished well before A's backlog drained
            assert b_last < a_last, done
            assert b_last < len(done) - 5, done
        finally:
            s.stop()

    def test_exception_propagates_and_tokens_charged(self):
        s = TokenPriorityScheduler(num_threads=2)
        s.start()
        try:
            fut = s.submit(lambda: (_ for _ in ()).throw(RuntimeError("r")),
                           table="t")
            with pytest.raises(RuntimeError):
                fut.result(5)
            assert s.submit(lambda: b"fine", table="t").result(5) == b"fine"
        finally:
            s.stop()


class TestBinaryWorkload:
    def test_secondary_confined(self):
        s = BinaryWorkloadScheduler(num_threads=4, secondary_threads=1)
        try:
            running = []
            peak = []
            lock = threading.Lock()

            def slow():
                with lock:
                    running.append(1)
                    peak.append(len(running))
                time.sleep(0.05)
                with lock:
                    running.pop()
                return b""

            futs = [s.submit(slow, workload="secondary") for _ in range(4)]
            for f in futs:
                f.result(5)
            assert max(peak) == 1  # secondary never exceeds its 1 thread

            peak.clear()
            futs = [s.submit(slow, workload="primary") for _ in range(4)]
            for f in futs:
                f.result(5)
            assert max(peak) > 1  # primary parallelism intact
        finally:
            s.stop()


class TestMetricsAttach:
    """Regression: the lock-discipline analyzer caught attach_metrics
    rebuilding the inflight counter AND its lock on every call — a
    re-attach while queries were in flight (role rebuild, tests) reset
    the unguarded counter and swapped the lock out from under the
    concurrent done-callbacks, skewing scheduler_inflight forever."""

    def test_reattach_mid_flight_keeps_counter_and_lock(self):
        from concurrent.futures import Future
        from pinot_tpu.utils.metrics import MetricsRegistry

        s = FCFSQueryScheduler(num_threads=1)
        s.attach_metrics(MetricsRegistry())
        lock0 = s._mlock
        fut = Future()
        s._track(fut)              # one query in flight
        assert s._inflight == 1

        m2 = MetricsRegistry()
        s.attach_metrics(m2)       # re-attach MID-FLIGHT (role rebuild)
        assert s._mlock is lock0   # done-callbacks still hold this lock
        assert s._inflight == 1    # counter not reset

        fut.set_result(b"")        # in-flight query completes
        assert s._inflight == 0    # gauge returns to zero, not -1

    def test_concurrent_track_vs_reattach_never_skews(self):
        from pinot_tpu.utils.metrics import MetricsRegistry

        s = FCFSQueryScheduler(num_threads=4)
        m = MetricsRegistry()
        s.attach_metrics(m)
        stop = threading.Event()

        def reattacher():
            while not stop.is_set():
                s.attach_metrics(m)

        t = threading.Thread(target=reattacher, daemon=True)
        t.start()
        try:
            for _ in range(50):
                futs = [s.submit(lambda: b"") for _ in range(8)]
                for f in futs:
                    f.result(5)
        finally:
            stop.set()
            t.join(5)
            s.stop()
        # every submit's done-callback found the ONE lock/counter pair
        assert s._inflight == 0
        assert m.gauge("scheduler_inflight") == 0


class TestBrokerStopVsRebuild:
    """Regression: BrokerRole.stop iterated the LIVE connections dict
    while the coordinator-watch thread's rebuild() swapped entries into
    it under _rebuild_lock — a watch firing mid-shutdown raised
    'dictionary changed size during iteration' and leaked the unclosed
    swapped-in channels (found by the lock-discipline analyzer)."""

    def _bare_broker(self):
        from pinot_tpu.cluster.roles import BrokerRole

        class _Noop:
            def close(self):
                pass

            def stop(self):
                pass

        b = object.__new__(BrokerRole)
        b.client = _Noop()
        b.http = _Noop()
        b.connections = {}
        b._rebuild_lock = threading.Lock()
        return b

    def test_stop_survives_concurrent_rebuild_mutation(self):
        class _Conn:
            closed = 0

            def close(self):
                _Conn.closed += 1

        b = self._bare_broker()
        stop = threading.Event()

        def churner():
            """The watch thread: swaps connection entries under the
            rebuild lock, exactly as rebuild() does."""
            i = 0
            while not stop.is_set():
                with b._rebuild_lock:
                    b.connections[f"server-{i % 7}"] = _Conn()
                    i += 1

        t = threading.Thread(target=churner, daemon=True)
        t.start()
        try:
            for _ in range(200):
                b.stop()           # raced the churner pre-fix
        finally:
            stop.set()
            t.join(5)
        assert _Conn.closed > 0
