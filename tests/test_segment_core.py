"""Segment core round-trip tests.

Mirrors the reference's segment reader/creator unit tests
(pinot-segment-local/src/test — e.g. forward index + dictionary round-trips).
"""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, IndexingConfig,
                              Schema, TableConfig)
from pinot_tpu.segment import bitpack, fwd
from pinot_tpu.segment.bitmap import Bitmap
from pinot_tpu.segment.creator import build_segment
from pinot_tpu.segment.dictionary import Dictionary
from pinot_tpu.segment.indexes import BloomFilter, InvertedIndex, RangeIndex, SortedIndex
from pinot_tpu.segment.loader import load_segment

RNG = np.random.default_rng(42)


# ---------------------------------------------------------------------------
# bitpack
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bits", [1, 2, 3, 5, 7, 8, 13, 16, 17, 24, 31, 32])
def test_bitpack_roundtrip(bits):
    n = 1001
    hi = min((1 << bits) - 1, (1 << 31) - 1)
    vals = RNG.integers(0, hi + 1, size=n, dtype=np.int64).astype(np.uint32)
    packed = bitpack.pack(vals, bits)
    assert len(packed) == bitpack.packed_size(n, bits)
    out = bitpack.unpack(packed, n, bits)
    np.testing.assert_array_equal(out, vals.astype(np.int32))


@pytest.mark.parametrize("bits", [1, 3, 7, 11, 16, 20, 32])
def test_pack_to_words_roundtrip(bits):
    n = 257
    hi = min((1 << bits) - 1, (1 << 31) - 1)
    vals = RNG.integers(0, hi + 1, size=n, dtype=np.int64).astype(np.uint32)
    words = bitpack.pack_to_words(vals, bits)
    out = bitpack.unpack_from_words(words, n, bits)
    np.testing.assert_array_equal(out, vals.astype(np.int32))


def test_num_bits():
    assert bitpack.num_bits(1) == 1
    assert bitpack.num_bits(2) == 1
    assert bitpack.num_bits(3) == 2
    assert bitpack.num_bits(256) == 8
    assert bitpack.num_bits(257) == 9


# ---------------------------------------------------------------------------
# bitmap
# ---------------------------------------------------------------------------

def test_bitmap_ops():
    n = 1003
    a_idx = RNG.choice(n, size=200, replace=False)
    b_idx = RNG.choice(n, size=300, replace=False)
    a = Bitmap.from_indices(n, a_idx)
    b = Bitmap.from_indices(n, b_idx)
    assert a.cardinality() == 200
    sa, sb = set(a_idx.tolist()), set(b_idx.tolist())
    assert set((a & b).to_indices().tolist()) == sa & sb
    assert set((a | b).to_indices().tolist()) == sa | sb
    assert set(a.invert().to_indices().tolist()) == set(range(n)) - sa
    assert set(a.andnot(b).to_indices().tolist()) == sa - sb
    rt = Bitmap.from_bytes(n, a.to_bytes())
    assert rt == a
    assert a.contains(int(a_idx[0]))


def test_bitmap_all_set_trim():
    bm = Bitmap.all_set(13)
    assert bm.cardinality() == 13
    assert bm.invert().cardinality() == 0


# ---------------------------------------------------------------------------
# dictionary
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dt,gen", [
    (DataType.INT, lambda: RNG.integers(-1000, 1000, 500).astype(np.int32)),
    (DataType.LONG, lambda: RNG.integers(-10**12, 10**12, 500).astype(np.int64)),
    (DataType.FLOAT, lambda: RNG.normal(size=500).astype(np.float32)),
    (DataType.DOUBLE, lambda: RNG.normal(size=500).astype(np.float64)),
    (DataType.STRING, lambda: np.array([f"val-{i % 37}" for i in range(500)], dtype=object)),
])
def test_dictionary_roundtrip(dt, gen):
    col = gen()
    d, ids = Dictionary.build(dt, col)
    # dictIds decode back to original values
    np.testing.assert_array_equal(d.get_values(ids), col)
    # sorted ⇒ searchsorted find works
    for v in col[:20]:
        di = d.index_of(v)
        assert di >= 0 and d.get_value(di) == (v.item() if isinstance(v, np.generic) else v)
    assert d.index_of("zzz-not-there" if dt is DataType.STRING else 10**15) == -1
    rt = Dictionary.from_bytes(dt, d.to_bytes(), d.cardinality)
    np.testing.assert_array_equal(rt.values, d.values)
    assert d.min_value == min(col.tolist())
    assert d.max_value == max(col.tolist())


# ---------------------------------------------------------------------------
# forward indexes
# ---------------------------------------------------------------------------

def test_raw_fixed_roundtrip():
    vals = RNG.normal(size=200_000).astype(np.float64)
    for comp in ("PASS_THROUGH", "GZIP", "LZ4"):
        buf = fwd.write_raw_fixed(vals, comp)
        out = fwd.read_raw_fixed(np.frombuffer(buf, dtype=np.uint8), len(vals), np.float64)
        np.testing.assert_array_equal(out, vals)


def test_raw_var_roundtrip():
    vals = [f"string-{i}-{'x' * (i % 50)}" for i in range(70_000)]
    buf = fwd.write_raw_var(vals, "GZIP", is_bytes=False)
    out = fwd.read_raw_var(np.frombuffer(buf, dtype=np.uint8), len(vals), False)
    assert list(out) == vals


def test_mv_dict_roundtrip():
    rows = [RNG.integers(0, 50, size=RNG.integers(0, 6)).astype(np.int32)
            for _ in range(1000)]
    buf = fwd.write_mv_dict(rows, bits=6)
    offsets, flat = fwd.read_mv_dict(np.frombuffer(buf, dtype=np.uint8), len(rows), 6)
    pos = 0
    for i, r in enumerate(rows):
        np.testing.assert_array_equal(flat[offsets[i]:offsets[i + 1]], r)
        pos += len(r)


# ---------------------------------------------------------------------------
# auxiliary indexes
# ---------------------------------------------------------------------------

def test_inverted_index():
    n, card = 5000, 17
    ids = RNG.integers(0, card, n).astype(np.int32)
    inv = InvertedIndex.from_bytes(
        np.frombuffer(InvertedIndex.build(ids, card, n).to_bytes(), dtype=np.uint8))
    for d in range(card):
        np.testing.assert_array_equal(np.sort(inv.doc_ids_for(d)),
                                      np.flatnonzero(ids == d))


def test_range_index():
    n, card = 20_000, 1000
    ids = RNG.integers(0, card, n).astype(np.int32)
    ri = RangeIndex.from_bytes(
        np.frombuffer(RangeIndex.build(ids, card, n).to_bytes(), dtype=np.uint8))
    lo, hi = 123, 777
    exact, cand = ri.query(lo, hi)
    truth = np.flatnonzero((ids >= lo) & (ids <= hi))
    # exact docs must all match; exact+verified(cand) == truth
    assert np.all((ids[exact] >= lo) & (ids[exact] <= hi))
    verified = cand[(ids[cand] >= lo) & (ids[cand] <= hi)]
    got = np.sort(np.concatenate([exact, verified]))
    np.testing.assert_array_equal(got, truth)


def test_sorted_index():
    ids = np.sort(RNG.integers(0, 20, 3000)).astype(np.int32)
    si = SortedIndex.from_bytes(
        np.frombuffer(SortedIndex.build(ids, 20).to_bytes(), dtype=np.uint8))
    for d in range(20):
        s, e = si.range_for(d)
        np.testing.assert_array_equal(np.arange(s, e), np.flatnonzero(ids == d))
    s, e = si.range_for_ids(3, 7)
    np.testing.assert_array_equal(np.arange(s, e), np.flatnonzero((ids >= 3) & (ids <= 7)))


def test_bloom_filter():
    vals = [f"key-{i}" for i in range(2000)]
    bf = BloomFilter.from_bytes(
        np.frombuffer(BloomFilter.build(vals).to_bytes(), dtype=np.uint8))
    assert all(bf.might_contain(v) for v in vals)
    fp = sum(bf.might_contain(f"other-{i}") for i in range(2000))
    assert fp < 400  # well under 20% false positives


# ---------------------------------------------------------------------------
# end-to-end segment build + load
# ---------------------------------------------------------------------------

def _make_schema():
    s = Schema("testTable")
    s.add_dimension("country", DataType.STRING)
    s.add_dimension("city", DataType.STRING)
    s.add_dimension("year", DataType.INT)
    s.add_metric("revenue", DataType.DOUBLE)
    s.add_metric("clicks", DataType.LONG)
    s.add_dimension("tags", DataType.STRING, single_value=False)
    s.add_date_time("ts", DataType.TIMESTAMP)
    return s


def test_segment_build_and_load(tmp_path):
    n = 4000
    schema = _make_schema()
    cfg = TableConfig(
        name="testTable",
        indexing=IndexingConfig(
            inverted_index_columns=["city"],
            range_index_columns=["year"],
            bloom_filter_columns=["country"],
            no_dictionary_columns=["revenue"],
        ),
    )
    cfg.retention.time_column = "ts"
    countries = RNG.choice(["US", "DE", "JP", "IN", "BR"], n)
    cities = RNG.choice([f"city{i}" for i in range(40)], n)
    years = RNG.integers(2000, 2025, n).astype(np.int32)
    revenue = RNG.normal(100, 20, n)
    clicks = RNG.integers(0, 10**6, n).astype(np.int64)
    tags = [list(RNG.choice(["a", "b", "c", "d"], RNG.integers(1, 4))) for _ in range(n)]
    ts = RNG.integers(1_600_000_000_000, 1_700_000_000_000, n).astype(np.int64)
    cols = {"country": countries, "city": cities, "year": years,
            "revenue": revenue, "clicks": clicks, "tags": tags, "ts": ts}

    seg_dir = str(tmp_path / "seg_0")
    build_segment(cfg, schema, cols, seg_dir, "testTable_seg_0")
    seg = load_segment(seg_dir)

    assert seg.num_docs == n
    assert seg.metadata.start_time == int(ts.min())
    assert seg.metadata.end_time == int(ts.max())

    # dict-encoded column round-trips
    np.testing.assert_array_equal(seg.data_source("country").values(), countries)
    np.testing.assert_array_equal(seg.data_source("year").values(), years)
    np.testing.assert_array_equal(seg.data_source("clicks").values(), clicks)
    # raw column round-trips
    np.testing.assert_array_equal(seg.data_source("revenue").values(), revenue)
    # MV column
    ds_tags = seg.data_source("tags")
    offsets = ds_tags.mv_offsets()
    vals = ds_tags.dictionary.get_values(ds_tags.dict_ids())
    for i in range(0, n, 97):
        assert list(vals[offsets[i]:offsets[i + 1]]) == tags[i]

    # metadata
    m = seg.metadata.columns["year"]
    assert m.min_value == int(years.min()) and m.max_value == int(years.max())
    assert m.cardinality == len(np.unique(years))

    # indexes
    inv = seg.data_source("city").inverted_index
    d = seg.data_source("city").dictionary
    some_city = cities[0]
    docs = inv.doc_ids_for(d.index_of(some_city))
    np.testing.assert_array_equal(np.sort(docs), np.flatnonzero(cities == some_city))
    assert seg.data_source("year").range_index is not None
    bf = seg.data_source("country").bloom_filter
    assert bf.might_contain("US") and not bf.might_contain("XX-nope")


def test_segment_nulls_and_sorted(tmp_path):
    n = 1000
    schema = Schema("t2")
    schema.add_dimension("k", DataType.INT)
    schema.add_metric("v", DataType.DOUBLE)
    cfg = TableConfig(name="t2")
    k = np.sort(RNG.integers(0, 50, n)).astype(np.int32)
    v = [float(i) if i % 10 else None for i in range(n)]
    seg_dir = str(tmp_path / "seg")
    build_segment(cfg, schema, {"k": k, "v": v}, seg_dir, "t2_seg_0")
    seg = load_segment(seg_dir)
    # sorted column detected, sorted index usable
    assert seg.metadata.columns["k"].is_sorted
    si = seg.data_source("k").sorted_index
    s, e = si.range_for_ids(0, 5)
    d = seg.data_source("k").dictionary
    hi_val = d.get_value(5)
    np.testing.assert_array_equal(np.arange(s, e), np.flatnonzero(k <= hi_val))
    # nulls recorded, defaults substituted
    nv = seg.data_source("v").null_value_vector
    assert nv is not None and nv.cardinality() == n // 10
    assert seg.data_source("v").values()[0] == 0.0  # metric default null
