"""Star-tree build + traversal + query execution vs the scan path
(BASELINE config #5 territory; ref StarTreeClusterIntegrationTest)."""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              StarTreeIndexConfig, TableConfig, TableType)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment

NUM_DOCS = 20_000


@pytest.fixture(scope="module")
def seg_pair(tmp_path_factory):
    """Same data twice: with and without a star-tree."""
    tmp = tmp_path_factory.mktemp("startree")
    schema = Schema("st", [
        FieldSpec("country", DataType.STRING),
        FieldSpec("browser", DataType.STRING),
        FieldSpec("locale", DataType.STRING),
        FieldSpec("impressions", DataType.LONG, FieldType.METRIC),
        FieldSpec("cost", DataType.DOUBLE, FieldType.METRIC),
    ])
    rng = np.random.default_rng(5)
    cols = {
        "country": [f"c{v}" for v in rng.integers(0, 20, NUM_DOCS)],
        "browser": [f"b{v}" for v in rng.integers(0, 6, NUM_DOCS)],
        "locale": [f"l{v}" for v in rng.integers(0, 10, NUM_DOCS)],
        "impressions": rng.integers(0, 1000, NUM_DOCS).astype(np.int64),
        "cost": rng.random(NUM_DOCS) * 100,
    }
    tc_plain = TableConfig("st", TableType.OFFLINE)
    SegmentCreator(tc_plain, schema).build(dict(cols), str(tmp / "plain"), "st_plain")

    tc_tree = TableConfig("st", TableType.OFFLINE)
    tc_tree.indexing.star_tree_configs = [StarTreeIndexConfig(
        dimensions_split_order=["country", "browser", "locale"],
        function_column_pairs=["SUM__impressions", "MAX__cost", "SUM__cost"],
        max_leaf_records=10)]
    SegmentCreator(tc_tree, schema).build(dict(cols), str(tmp / "tree"), "st_tree")
    return (load_segment(str(tmp / "plain")), load_segment(str(tmp / "tree")),
            cols)


QUERIES = [
    "SELECT SUM(impressions) FROM st",
    "SELECT COUNT(*), SUM(impressions), MAX(cost) FROM st",
    "SELECT SUM(impressions) FROM st WHERE country = 'c3'",
    "SELECT SUM(impressions) FROM st WHERE country IN ('c1','c2','c3') AND browser = 'b2'",
    "SELECT SUM(impressions), AVG(cost) FROM st WHERE locale = 'l5'",
    "SELECT country, SUM(impressions) FROM st GROUP BY country ORDER BY country LIMIT 100",
    "SELECT country, browser, COUNT(*), SUM(cost) FROM st WHERE locale = 'l1' "
    "GROUP BY country, browser ORDER BY country, browser LIMIT 200",
    "SELECT browser, MAX(cost) FROM st WHERE country BETWEEN 'c1' AND 'c4' "
    "GROUP BY browser ORDER BY browser LIMIT 100",
]


class TestStarTreeParity:
    @pytest.mark.parametrize("sql", QUERIES)
    def test_tree_matches_scan(self, seg_pair, sql):
        plain, tree, _ = seg_pair
        scan = QueryExecutor([plain], use_tpu=False).execute(sql)
        st = QueryExecutor([tree], use_tpu=False).execute(sql)
        assert scan.result_table.rows is not None
        rows_a = sorted(map(str, scan.result_table.rows))
        rows_b = sorted(map(str, st.result_table.rows))
        for a, b in zip(rows_a, rows_b):
            assert _rows_close(eval(a), eval(b)), (sql, a, b)
        assert len(rows_a) == len(rows_b), sql

    def test_tree_actually_used(self, seg_pair):
        plain, tree, _ = seg_pair
        st = QueryExecutor([tree], use_tpu=False).execute(
            "SELECT SUM(impressions) FROM st WHERE country = 'c3'")
        scan = QueryExecutor([plain], use_tpu=False).execute(
            "SELECT SUM(impressions) FROM st WHERE country = 'c3'")
        # pre-agg records scanned must be far fewer than raw docs matched
        assert 0 < st.stats.num_docs_scanned < scan.stats.num_docs_scanned / 5

    def test_opt_out(self, seg_pair):
        _, tree, cols = seg_pair
        r = QueryExecutor([tree], use_tpu=False).execute(
            "SELECT SUM(impressions) FROM st OPTION(useStarTree=false)")
        imp = np.asarray(cols["impressions"])
        assert r.rows[0][0] == pytest.approx(float(imp.sum()))
        assert r.stats.num_docs_scanned == NUM_DOCS

    def test_unsupported_shape_falls_back(self, seg_pair):
        _, tree, cols = seg_pair
        # DISTINCTCOUNT can't be served from pre-agg records
        r = QueryExecutor([tree], use_tpu=False).execute(
            "SELECT DISTINCTCOUNT(country) FROM st")
        assert r.rows[0][0] == len(set(cols["country"]))

    def test_or_filter_falls_back(self, seg_pair):
        _, tree, cols = seg_pair
        c = np.asarray(cols["country"])
        b = np.asarray(cols["browser"])
        imp = np.asarray(cols["impressions"])
        r = QueryExecutor([tree], use_tpu=False).execute(
            "SELECT SUM(impressions) FROM st WHERE country = 'c1' OR browser = 'b1'")
        want = float(imp[(c == "c1") | (b == "b1")].sum())
        assert r.rows[0][0] == pytest.approx(want)


def _rows_close(a, b):
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if isinstance(x, float) or isinstance(y, float):
            if not (abs(float(x) - float(y)) <= 1e-6 * max(1.0, abs(float(x)))):
                return False
        elif x != y:
            return False
    return True
