"""Device star-tree pre-aggregation (ISSUE 16).

The engine's `_prepare_startree` leg: host tree traversal + device
residual aggregation through the unified kernel factory. Covers

  * parity — device pre-agg vs host star-tree vs scan path, identical
    rows (1e-6 relative, the device-parity standard) on randomized data,
    flat and grouped, including AVG's (SUM, COUNT) decomposition
  * fit-check edges — FILTER aggs, OR filters, non-tree-dim predicates,
    `OPTION(useStarTree=false)`: each answers correctly via the scan
    path and meters its `startree_fallback{reason=}`; the
    `pinot.server.startree.enabled` knob disables the leg wholesale
  * coalescing — fingerprint-equal concurrent star-tree queries share
    batched launches (`dispatch_batch_size` > 1) with ZERO steady-state
    retraces once the shape buckets are warm
  * warmup — `SegmentWarmup` prestages the pre-agg pseudo-columns, so
    the first routed query ships zero column bytes
  * the `bench.py --startree` acceptance scenario at smoke scale
"""
import threading

import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              StarTreeIndexConfig, TableConfig, TableType)
from pinot_tpu.ops import kernels
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.context import QueryContext
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.config import PinotConfiguration

NUM_DOCS = 3_000   # per segment
NUM_SEGS = 2


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    """Identical data twice: plain segments and tree-carrying segments.
    `platform` stays OUT of the split order — the non-tree-dim
    fallback case."""
    tmp = tmp_path_factory.mktemp("startree_device")
    schema = Schema("st", [
        FieldSpec("country", DataType.STRING),
        FieldSpec("browser", DataType.STRING),
        FieldSpec("locale", DataType.STRING),
        FieldSpec("platform", DataType.STRING),
        FieldSpec("impressions", DataType.LONG, FieldType.METRIC),
        FieldSpec("cost", DataType.DOUBLE, FieldType.METRIC),
    ])
    tc_plain = TableConfig("st", TableType.OFFLINE)
    tc_tree = TableConfig("st", TableType.OFFLINE)
    tc_tree.indexing.star_tree_configs = [StarTreeIndexConfig(
        dimensions_split_order=["country", "browser", "locale"],
        function_column_pairs=["SUM__impressions", "MAX__cost",
                               "SUM__cost"],
        max_leaf_records=10)]
    plain, tree = [], []
    for i in range(NUM_SEGS):
        rng = np.random.default_rng(17 + i)
        cols = {
            "country": [f"c{v}" for v in rng.integers(0, 12, NUM_DOCS)],
            "browser": [f"b{v}" for v in rng.integers(0, 5, NUM_DOCS)],
            "locale": [f"l{v}" for v in rng.integers(0, 8, NUM_DOCS)],
            "platform": [f"p{v}" for v in rng.integers(0, 3, NUM_DOCS)],
            "impressions": rng.integers(0, 1000, NUM_DOCS).astype(np.int64),
            "cost": rng.random(NUM_DOCS) * 100,
        }
        SegmentCreator(tc_plain, schema).build(
            dict(cols), str(tmp / f"plain_{i}"), f"st_plain_{i}")
        SegmentCreator(tc_tree, schema).build(
            dict(cols), str(tmp / f"tree_{i}"), f"st_tree_{i}")
        plain.append(load_segment(str(tmp / f"plain_{i}")))
        tree.append(load_segment(str(tmp / f"tree_{i}")))
    return plain, tree


QUERIES = [
    "SELECT SUM(impressions) FROM st",
    "SELECT COUNT(*), SUM(impressions), MAX(cost) FROM st",
    "SELECT SUM(impressions) FROM st WHERE country = 'c3'",
    "SELECT SUM(impressions) FROM st "
    "WHERE country IN ('c1','c2','c3') AND browser = 'b2'",
    "SELECT SUM(impressions), AVG(cost) FROM st WHERE locale = 'l5'",
    "SELECT AVG(impressions), AVG(cost) FROM st WHERE browser = 'b1'",
    "SELECT country, SUM(impressions) FROM st "
    "GROUP BY country ORDER BY country LIMIT 100",
    "SELECT country, browser, COUNT(*), SUM(cost) FROM st "
    "WHERE locale = 'l1' GROUP BY country, browser "
    "ORDER BY country, browser LIMIT 200",
    "SELECT browser, MAX(cost) FROM st WHERE country BETWEEN 'c1' AND 'c4' "
    "GROUP BY browser ORDER BY browser LIMIT 100",
]


def _rows_close(a, b):
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if isinstance(x, float) or isinstance(y, float):
            if not (abs(float(x) - float(y))
                    <= 1e-6 * max(1.0, abs(float(x)))):
                return False
        elif x != y:
            return False
    return True


def _assert_same_rows(resp_a, resp_b, sql):
    assert not resp_a.exceptions and not resp_b.exceptions, sql
    ra = sorted(map(str, resp_a.result_table.rows))
    rb = sorted(map(str, resp_b.result_table.rows))
    assert len(ra) == len(rb), (sql, ra, rb)
    for a, b in zip(ra, rb):
        assert _rows_close(eval(a), eval(b)), (sql, a, b)


def _engine(name, **overrides):
    return TpuOperatorExecutor(
        config=PinotConfiguration(overrides=overrides),
        metrics_labels={"st_test": name})


def _meter(eng, name, reason=None):
    labels = {"st_test": eng._labels["st_test"]}
    if reason is not None:
        labels["reason"] = reason
    return eng._metrics.meter(name, labels=labels)


class TestDeviceParity:
    @pytest.mark.parametrize("sql", QUERIES)
    def test_device_tree_vs_host_tree_vs_scan(self, segs, sql):
        plain, tree = segs
        dev = QueryExecutor(tree, use_tpu=True).execute(sql)
        host = QueryExecutor(tree, use_tpu=False).execute(sql)
        scan = QueryExecutor(plain, use_tpu=False).execute(sql)
        _assert_same_rows(dev, host, sql)
        _assert_same_rows(dev, scan, sql)

    def test_served_meter_and_preagg_stats(self, segs):
        """The pre-agg leg actually serves (startree_served moves) and
        scans pre-agg records, not raw docs."""
        _, tree = segs
        eng = _engine("served")
        ex = QueryExecutor(tree, use_tpu=True, engine=eng)
        r = ex.execute("SELECT SUM(impressions) FROM st WHERE country = 'c3'")
        assert not r.exceptions
        assert _meter(eng, "startree_served") == 1
        assert 0 < r.stats.num_docs_scanned < NUM_SEGS * NUM_DOCS / 2

    def test_knob_disables_the_leg(self, segs):
        """pinot.server.startree.enabled=false: same rows via the scan
        path, nothing served from pre-agg."""
        plain, tree = segs
        eng = _engine("knob", **{"pinot.server.startree.enabled": False})
        ex = QueryExecutor(tree, use_tpu=True, engine=eng)
        sql = "SELECT SUM(impressions), COUNT(*) FROM st WHERE browser = 'b2'"
        _assert_same_rows(ex.execute(sql),
                          QueryExecutor(plain, use_tpu=False).execute(sql),
                          sql)
        assert _meter(eng, "startree_served") == 0


class TestFitFallback:
    """Queries a tree can't serve answer correctly via the scan path and
    meter their startree_fallback reason."""

    CASES = [
        ("SELECT SUM(impressions) FROM st OPTION(useStarTree=false)",
         "disabled"),
        ("SELECT SUM(impressions) FILTER (WHERE browser = 'b1'), COUNT(*) "
         "FROM st", "aggregation"),
        ("SELECT SUM(impressions) FROM st "
         "WHERE country = 'c1' OR browser = 'b1'", "filter"),
        ("SELECT SUM(impressions) FROM st WHERE platform = 'p1'", "filter"),
    ]

    @pytest.mark.parametrize("sql,reason", CASES)
    def test_fallback_reason_and_parity(self, segs, sql, reason):
        plain, tree = segs
        eng = _engine(f"fb_{reason}_{abs(hash(sql)) % 1000}")
        before = _meter(eng, "startree_fallback", reason=reason)
        dev = QueryExecutor(tree, use_tpu=True, engine=eng).execute(sql)
        scan = QueryExecutor(plain, use_tpu=False).execute(sql)
        _assert_same_rows(dev, scan, sql)
        assert _meter(eng, "startree_fallback", reason=reason) > before, sql
        assert _meter(eng, "startree_served") == 0


class TestCoalesce:
    def test_fingerprint_equal_queries_batch_with_zero_retraces(self, segs):
        """Concurrent star-tree queries that differ only in predicate
        constants share the (plan fingerprint, shape bucket) coalesce
        key: batched launches form, and once the pow2 batch buckets are
        traced, the measured window compiles NOTHING."""
        import contextlib

        import jax

        from pinot_tpu.ops import dispatch as dispatch_mod
        _, tree = segs
        clients = 6
        eng = _engine("coalesce")
        ex = QueryExecutor(tree, use_tpu=True, engine=eng)
        sqls = [f"SELECT SUM(impressions), COUNT(*) FROM st "
                f"WHERE country = 'c{i}'" for i in range(clients)]
        for sql in sqls:   # stage blocks + params, trace the single path
            assert not ex.execute(sql).exceptions
        launch = eng._prepare_startree(
            tree, QueryContext.from_sql(sqls[0]))[4]
        guard = dispatch_mod._CPU_COLLECTIVE_LOCK if launch.collective \
            else contextlib.nullcontext()
        b = 2
        while b <= dispatch_mod._pow2(clients):
            kern = launch.factory(b, False)
            with guard:
                jax.block_until_ready(kern(
                    launch.cols, (launch.params,) * b, launch.num_docs,
                    D=launch.D, G=launch.G))
            b *= 2

        traces0 = kernels.trace_count()
        labels = {"st_test": "coalesce"}
        t0 = eng._metrics.timer("dispatch_batch_size", labels=labels)
        count0, max0 = t0.count, t0.max_ms
        rounds = 8

        def client(ci):
            for j in range(rounds):
                ex.execute(sqls[(ci + j) % clients])

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert kernels.trace_count() - traces0 == 0
        t1 = eng._metrics.timer("dispatch_batch_size", labels=labels)
        assert t1.count > count0
        assert max(t1.max_ms, max0) >= 2, \
            "fingerprint-equal star-tree queries never coalesced"


class TestWarmupPrestage:
    def test_warmup_prestages_preagg_columns(self, segs):
        """SegmentWarmup's replay prestages the star-tree pseudo-columns
        (engine.prestage takes the star-tree leg for fitted plans), so
        the first routed query ships zero column bytes."""
        from pinot_tpu.cache.segment_cache import SegmentResultCache
        from pinot_tpu.cache.warmup import FingerprintLog, SegmentWarmup
        from pinot_tpu.ops import residency
        _, tree = segs
        eng = _engine("warmup")
        log = FingerprintLog()
        sql = "SELECT SUM(impressions), COUNT(*) FROM st WHERE country = 'c2'"
        log.record("st", QueryContext.from_sql(sql).fingerprint(), sql)
        warm = SegmentWarmup(log, SegmentResultCache(), use_tpu=True,
                             engine_fn=lambda: eng)
        assert warm.warm("st", tree[0]) == 1
        # the seeded replay went through the pre-agg leg and admitted
        # the __startree__ pseudo-columns into residency
        assert _meter(eng, "startree_served") == 1
        assert eng.residency.resident_for(tree[0].name) > 0
        b0 = residency.column_transfer_bytes()
        r = QueryExecutor([tree[0]], use_tpu=True, engine=eng).execute(sql)
        assert not r.exceptions
        assert _meter(eng, "startree_served") == 2
        assert residency.column_transfer_bytes() - b0 == 0


class TestBenchSmoke:
    def test_startree_bench_smoke(self, tmp_path):
        """The --startree acceptance scenario at smoke scale: scaling
        A/B (pre-agg vs scan, parity inside), coalescing with zero
        steady-state retraces asserted inside."""
        import importlib
        import json
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench = importlib.import_module("bench")
        out = str(tmp_path / "BENCH_startree_smoke.json")
        bench.startree_main(smoke=True, out_path=out)
        with open(out) as f:
            data = json.load(f)
        assert data["coalesce"]["retraces_steady"] == 0
        assert data["coalesce"]["batch_size_max"] >= 2
