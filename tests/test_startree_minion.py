"""Minion StarTreeBuildTask (ISSUE 16): grow star-trees on sealed
segments without re-ingest.

  * generator — `taskConfigs` tables emit one task over ONLINE segments
    whose metadata carries no tree; a second tick generates NOTHING
    (the metadata "starTree" marker is the convergence signal)
  * executor — rebuilds each segment from its own columns under the
    grafted tree config, commits via publish/retire; the rebuilt
    segment serves the DEVICE pre-agg path
  * chaos, `minion.startree.build` — a SimulatedCrash before the
    rebuild leaves the source segment serving via the scan path; the
    re-leased task rebuilds BYTE-IDENTICAL tree buffers (deterministic
    build + output names)
  * chaos, `controller.segment.replace` — a permanently failing swap
    exhausts retries to FAILED with the source segment still routed and
    serving; disarm + resubmit converges onto the tree segments
"""
import os
import time

import numpy as np
import pytest

from pinot_tpu.controller.cluster_state import ClusterState, SegmentState
from pinot_tpu.controller.task_manager import (COMPLETED, FAILED, PENDING,
                                               TaskManager)
from pinot_tpu.controller.tasks import TaskConfig, TaskContext, run_task
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment import index_types as it
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import (FailpointError, SimulatedCrash,
                                        failpoints)

TREE_CFG = {"dimensionsSplitOrder": ["d"],
            "functionColumnPairs": ["SUM__m", "MAX__m"],
            "maxLeafRecords": 5}


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def make_schema():
    return Schema("ct", [
        FieldSpec("d", DataType.STRING),
        FieldSpec("ts", DataType.LONG, FieldType.DATE_TIME),
        FieldSpec("m", DataType.LONG, FieldType.METRIC),
    ])


def build_seg(tmp, name, n=100, seed=0, ts_base=0):
    """A sealed segment WITHOUT a tree (the seal path had no config)."""
    rng = np.random.default_rng(seed)
    cols = {"d": [f"k{v}" for v in rng.integers(0, 5, n)],
            "ts": (ts_base + np.arange(n)).astype(np.int64),
            "m": rng.integers(0, 50, n).astype(np.int64)}
    out = str(tmp / name)
    SegmentCreator(TableConfig("ct"), make_schema()).build(cols, out, name)
    return out


def setup_state(tmp, n_segments=2, table_type="REALTIME"):
    cfg = TableConfig("ct")
    cfg.task_configs = {"StarTreeBuildTask": {
        "starTreeIndexConfigs": [TREE_CFG]}}
    state = ClusterState()
    state.add_table(cfg, make_schema())
    for i in range(n_segments):
        d = build_seg(tmp, f"s{i}", seed=i, ts_base=i * 1000)
        m = load_segment(d).metadata
        state.upsert_segment(SegmentState(
            f"s{i}", f"ct_{table_type}", [], dir_path=d, num_docs=100,
            start_time=m.start_time, end_time=m.end_time))
    return state


def _manager(state):
    return TaskManager(state, config=PinotConfiguration(overrides={
        "pinot.controller.task.generators.enabled": True,
        "pinot.controller.task.retry.backoff.seconds": 0.0}))


def _tree_buffers(seg):
    """Raw star-tree index bytes — the byte-identity unit."""
    out = []
    for ti in range(len(seg.star_tree.trees)):
        out.append(bytes(seg.dir.get_buffer(f"__startree_{ti}",
                                            it.STARTREE)))
        out.append(bytes(seg.dir.get_buffer(f"__startree_{ti}",
                                            it.STARTREE_DATA)))
    return out


class TestGeneratorAndBuild:
    def test_build_converges_and_serves_device_path(self, tmp_path):
        state = setup_state(tmp_path)
        tm = _manager(state)
        assert tm.run_once()["generated"] == 1
        task = tm.queue.lease("w0")
        res = run_task(
            TaskConfig(task.task_type, task.table, list(task.segments),
                       dict(task.params), task_id=task.task_id),
            TaskContext(state, str(tmp_path / "out"),
                        task_id=task.task_id))
        assert sorted(res["builtSegments"]) == ["s0_sttree", "s1_sttree"]
        tm.queue.complete(task.task_id, "w0", res)
        # source segments retired, rebuilt ones registered with trees
        names = {s.name for s in state.table_segments("ct_REALTIME")}
        assert names == {"s0_sttree", "s1_sttree"}
        rebuilt = [load_segment(state.segments["ct_REALTIME"][n].dir_path)
                   for n in sorted(names)]
        for seg in rebuilt:
            assert seg.star_tree is not None and seg.star_tree.trees
            assert seg.num_docs == 100
        # the rebuilt segments serve the DEVICE pre-agg leg
        from pinot_tpu.ops.engine import TpuOperatorExecutor
        eng = TpuOperatorExecutor(
            metrics_labels={"st_test": "minion_serve"})
        ex = QueryExecutor(rebuilt, use_tpu=True, engine=eng)
        r = ex.execute("SELECT SUM(m), COUNT(*) FROM ct WHERE d = 'k1'")
        assert not r.exceptions
        assert eng._metrics.meter(
            "startree_served", labels={"st_test": "minion_serve"}) == 1
        # parity with a raw scan over the ORIGINAL segments
        orig = [load_segment(str(tmp_path / f"s{i}")) for i in range(2)]
        want = QueryExecutor(orig, use_tpu=False).execute(
            "SELECT SUM(m), COUNT(*) FROM ct WHERE d = 'k1'")
        assert r.result_table.rows == want.result_table.rows
        # second tick: metadata "starTree" marker -> nothing to do
        assert tm.run_once()["generated"] == 0

    def test_no_tree_config_generates_nothing(self, tmp_path):
        state = setup_state(tmp_path)
        state.tables["ct"].task_configs = {"StarTreeBuildTask": {}}
        assert _manager(state).run_once()["generated"] == 0

    def test_upsert_table_generates_nothing(self, tmp_path):
        from pinot_tpu.models import UpsertConfig
        state = setup_state(tmp_path)
        state.tables["ct"].upsert = UpsertConfig(mode="FULL")
        assert _manager(state).run_once()["generated"] == 0


class TestBuildChaos:
    def _run_flow(self, tmp_path, tag, chaos):
        """generate -> lease -> (crash -> expire -> re-lease) -> build;
        returns the rebuilt segments' tree buffers."""
        tmp = tmp_path / tag
        tmp.mkdir()
        state = setup_state(tmp)
        tm = _manager(state)
        assert tm.run_once()["generated"] == 1
        (entry,) = tm.queue.list(PENDING)
        task = tm.queue.lease("w0", lease_ttl_s=0.01)
        cfg = TaskConfig(task.task_type, task.table, list(task.segments),
                         dict(task.params), task_id=task.task_id)
        ctx = TaskContext(state, str(tmp / "out"), task_id=task.task_id)
        if chaos:
            failpoints.arm("minion.startree.build",
                           error=SimulatedCrash("chaos kill"), times=1)
            with pytest.raises(SimulatedCrash):
                run_task(cfg, ctx)
            # the crash fired BEFORE any rebuild: sources untouched,
            # still serving via the scan path
            segs = [load_segment(s.dir_path)
                    for s in state.table_segments("ct_REALTIME")]
            assert {s.name for s in segs} == {"s0", "s1"}
            r = QueryExecutor(segs, use_tpu=False).execute(
                "SELECT COUNT(*) FROM ct")
            assert r.rows[0][0] == 200
            # worker vanished: the lease expires and requeues the task
            time.sleep(0.02)
            assert tm.queue.expire_leases() == [entry.task_id]
            task = tm.queue.lease("w1")
            assert task.task_id == entry.task_id
        res = run_task(cfg, ctx)
        tm.queue.complete(task.task_id, task.worker, res)
        assert sorted(res["builtSegments"]) == ["s0_sttree", "s1_sttree"]
        return {
            n: _tree_buffers(load_segment(
                state.segments["ct_REALTIME"][n].dir_path))
            for n in res["builtSegments"]}

    def test_crashed_build_releases_and_rebuilds_byte_identical(
            self, tmp_path):
        baseline = self._run_flow(tmp_path, "nochaos", chaos=False)
        chaosed = self._run_flow(tmp_path, "chaos", chaos=True)
        assert baseline == chaosed  # tree BYTES, not just answers


class TestSwapChaos:
    def _cluster(self, tmp_path):
        from pinot_tpu.cluster.mini import MiniCluster
        c = MiniCluster(num_servers=1, minions=1,
                        config=PinotConfiguration(overrides={
                            "pinot.controller.task.max.attempts": 2,
                            "pinot.controller.task.retry.backoff.seconds":
                                0.05,
                            "pinot.minion.poll.seconds": 0.05,
                            "pinot.minion.heartbeat.seconds": 0.2}))
        c.start()
        cfg = TableConfig("ct")
        cfg.retention.time_column = "ts"
        c.add_table("ct", time_column="ts", table_config=cfg,
                    schema=make_schema())
        names = []
        for i in range(2):
            d = build_seg(tmp_path, f"seg_{i}", n=60, seed=i,
                          ts_base=i * 1000)
            c.add_segment("ct", load_segment(d), server_idx=0)
            names.append(f"seg_{i}")
        return c, names

    def test_mid_swap_failure_leaves_scan_serving_then_converges(
            self, tmp_path):
        """A permanently failing atomic swap exhausts retries: the task
        FAILS with the SOURCE segments still routed and answering (scan
        path). Disarm + resubmit converges onto the tree segments."""
        c, names = self._cluster(tmp_path)
        try:
            assert c.query("SELECT COUNT(*) FROM ct").rows[0][0] == 120
            failpoints.arm("controller.segment.replace",
                           error=FailpointError("swap chaos"))
            e = c.submit_task(TaskConfig(
                "StarTreeBuildTask", "ct_OFFLINE", names,
                {"starTreeIndexConfigs": [TREE_CFG]}))
            done = c.wait_task(e["task_id"], timeout_s=30)
            assert done["state"] == FAILED, done
            # sources still routed + serving (scan path, no trees)
            rt = c.routing.get_route("ct")
            assert sorted(rt.offline.segments) == names
            assert c.query("SELECT COUNT(*) FROM ct").rows[0][0] == 120
            # chaos over: the next attempt swaps in the rebuilt segments
            failpoints.clear()
            e = c.submit_task(TaskConfig(
                "StarTreeBuildTask", "ct_OFFLINE", names,
                {"starTreeIndexConfigs": [TREE_CFG]}))
            done = c.wait_task(e["task_id"], timeout_s=30)
            assert done["state"] == COMPLETED, done
            rt = c.routing.get_route("ct")
            assert sorted(rt.offline.segments) == \
                ["seg_0_sttree", "seg_1_sttree"]
            assert c.query("SELECT COUNT(*) FROM ct").rows[0][0] == 120
            from pinot_tpu.segment.fs import localize_segment
            (st0, _) = sorted(
                c.cluster_state.table_segments("ct_OFFLINE"),
                key=lambda s: s.name)
            local = localize_segment(st0.dir_path, str(tmp_path / "dl"))
            assert load_segment(local).star_tree.trees
        finally:
            c.stop()
