"""Repo-native static analysis (ISSUE 13): framework + six checkers.

Three layers of coverage:

  * fixture snippets with KNOWN violations per checker — positive,
    inline-suppressed, and clean variants — so a checker that silently
    stops finding its bug class fails here, not in production;
  * the framework itself: suppression parsing, baseline round-trip +
    stale detection, CLI exit codes;
  * THE TIER-1 GATE: zero unsuppressed findings across the real repo
    (accepted pre-existing findings live in ANALYSIS_BASELINE.json,
    each with a written reason) — plus behavioral tests arming the
    failpoint sites the `failpoints` checker found never armed.
"""
import json
import socket
import textwrap
import threading
import time

import pytest

from pinot_tpu.analysis import (
    ModuleIndex, load_baseline, run_analysis, write_baseline)
from pinot_tpu.analysis.__main__ import main as cli_main
from pinot_tpu.utils.failpoints import FailpointError, failpoints


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def _index(tmp_path, files):
    """Materialize a fixture repo tree and index it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return ModuleIndex(root=str(tmp_path))


def _run(tmp_path, files, checker, baseline=None):
    return run_analysis(_index(tmp_path, files), checkers=[checker],
                        baseline=baseline)


def _keys(report):
    return {f.key for f in report.unsuppressed}


# ---------------------------------------------------------------------------
# lock-discipline race detector
# ---------------------------------------------------------------------------

LOCKED_CLASS = '''
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._hits = 0

        def bump(self):
            with self._lock:
                self._hits += 1

        def peek(self):
            return self._hits{suffix}
'''


class TestLockChecker:
    def test_unguarded_read_of_guarded_attr_flagged(self, tmp_path):
        rep = _run(tmp_path, {
            "pinot_tpu/mod.py": LOCKED_CLASS.format(suffix="")}, "locks")
        assert _keys(rep) == {"Counter._hits:read@peek"}

    def test_inline_suppression_with_reason_accepted(self, tmp_path):
        rep = _run(tmp_path, {
            "pinot_tpu/mod.py": LOCKED_CLASS.format(
                suffix="  # lint: unlocked(meter only; torn reads ok)")},
            "locks")
        assert not rep.unsuppressed
        assert len(rep.inline_suppressed) == 1
        assert rep.inline_suppressed[0].reason == \
            "meter only; torn reads ok"

    def test_bare_suppression_without_reason_ignored(self, tmp_path):
        rep = _run(tmp_path, {
            "pinot_tpu/mod.py": LOCKED_CLASS.format(
                suffix="  # lint: unlocked()")}, "locks")
        assert _keys(rep) == {"Counter._hits:read@peek"}

    def test_read_under_lock_clean(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/mod.py": '''
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._hits = 0

                def bump(self):
                    with self._lock:
                        self._hits += 1

                def peek(self):
                    with self._lock:
                        return self._hits
        '''}, "locks")
        assert not rep.unsuppressed

    def test_named_closure_loses_lock_lambda_keeps_it(self, tmp_path):
        """The deferred-callback race class: a named closure defined
        under the lock runs LATER, lock released — flagged. A lambda
        (sorted key=) runs synchronously under the lock — clean."""
        rep = _run(tmp_path, {"pinot_tpu/mod.py": '''
            import threading

            class Book:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._rows = []

                def add(self, fut, row):
                    with self._lock:
                        self._rows.append(row)
                        self._rows.sort(key=lambda r: len(self._rows))

                        def done(_f):
                            self._rows.append(None)
                        fut.add_done_callback(done)
        '''}, "locks")
        # the closure's append is BOTH a read of the attr and a mutation
        assert _keys(rep) == {"Book._rows:write@add", "Book._rows:read@add"}

    def test_locked_suffix_is_a_scope_and_a_contract(self, tmp_path):
        """*_locked methods count as held-lock scopes; CALLING one from
        outside any lock scope breaks the suffix contract."""
        rep = _run(tmp_path, {"pinot_tpu/mod.py": '''
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._d = {}

                def put(self, k, v):
                    with self._lock:
                        self._put_locked(k, v)

                def _put_locked(self, k, v):
                    self._d[k] = v

                def sneaky(self, k, v):
                    self._put_locked(k, v)
        '''}, "locks")
        assert _keys(rep) == {"Store._put_locked:call@sneaky"}

    def test_ctor_writes_do_not_define_or_violate_guards(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/mod.py": '''
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._x = 0
        '''}, "locks")
        assert not rep.unsuppressed


# ---------------------------------------------------------------------------
# hang-risk lint
# ---------------------------------------------------------------------------

class TestHangChecker:
    def test_unbounded_result_wait_get_flagged(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/broker/mod.py": '''
            def gather(fut, ev, inbox):
                a = fut.result()
                ev.wait()
                b = inbox.queue.get()
                return a, b
        '''}, "hangs")
        assert _keys(rep) == {"gather:fut.result", "gather:ev.wait",
                              "gather:inbox.queue.get"}

    def test_bounded_variants_clean(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/broker/mod.py": '''
            def gather(fut, ev, inbox, deadline):
                a = fut.result(timeout=deadline)
                ev.wait(0.5)
                b = inbox.queue.get(timeout=deadline)
                c = inbox.queue.get(block=False)
                return a, b, c
        '''}, "hangs")
        assert not rep.unsuppressed

    def test_non_serving_modules_out_of_scope(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/segment/mod.py": '''
            def build(fut):
                return fut.result()
        '''}, "hangs")
        assert not rep.unsuppressed

    def test_duplicate_sites_get_distinct_keys(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/ops/mod.py": '''
            def drain(futs):
                return [f.result() for f in futs] + \\
                    [f.result() for f in reversed(futs)]
        '''}, "hangs")
        assert len(_keys(rep)) == 2


# ---------------------------------------------------------------------------
# failpoint-site registry
# ---------------------------------------------------------------------------

FP_FILES = {
    "pinot_tpu/utils/failpoints.py": '''
        SITES = {
            "good.site": "armed and fired",
            "unarmed.site": "fired but no test arms it",
            "phantom.site": "documented but never fired",
        }
    ''',
    "pinot_tpu/prod.py": '''
        def work():
            fire("good.site")
            fire("unarmed.site")
            fire("rogue.site")
    ''',
    "tests/test_chaos.py": '''
        def test_arming():
            with failpoints.armed("good.site", delay=0.1):
                pass
    ''',
}


class TestFailpointChecker:
    def test_three_promises(self, tmp_path):
        rep = _run(tmp_path, FP_FILES, "failpoints")
        assert _keys(rep) == {"undocumented:rogue.site",
                              "dead:phantom.site",
                              "unarmed:unarmed.site"}

    def test_missing_sites_table_is_itself_a_finding(self, tmp_path):
        files = dict(FP_FILES)
        files["pinot_tpu/utils/failpoints.py"] = "X = 1\n"
        rep = _run(tmp_path, files, "failpoints")
        assert _keys(rep) == {"SITES:missing"}


# ---------------------------------------------------------------------------
# config-knob checker
# ---------------------------------------------------------------------------

KNOB_FILES = {
    "pinot_tpu/utils/config.py": '''
        KEYS = {
            "pinot.good.knob": 1,
            "pinot.dead.knob": 2,
            "pinot.undocumented.knob": 3,
        }
    ''',
    "pinot_tpu/prod.py": '''
        def setup(cfg):
            a = cfg.get_int("pinot.good.knob")
            b = cfg.get("pinot.typo.knob")
            c = cfg.get_bool("pinot.undocumented.knob")
            return a, b, c
    ''',
    "README.md": "| `pinot.good.knob` | 1 | documented |\n",
}


class TestKnobChecker:
    def test_both_directions(self, tmp_path):
        rep = _run(tmp_path, KNOB_FILES, "knobs")
        assert _keys(rep) == {
            "unknown:pinot.typo.knob",       # read, not in catalog
            "dead:pinot.dead.knob",          # catalog, read nowhere
            "undocumented:pinot.dead.knob",  # catalog, not in README
            "undocumented:pinot.undocumented.knob",
        }

    def test_dynamic_key_composition_out_of_scope(self, tmp_path):
        files = dict(KNOB_FILES)
        files["pinot_tpu/prod.py"] = '''
            def setup(cfg, table):
                a = cfg.get_int("pinot.good.knob")
                b = cfg.get("pinot.good.knob." + table)
                c = cfg.get(f"pinot.undocumented.knob.{table}")
                return a, b, c
        '''
        rep = _run(tmp_path, files, "knobs")
        assert "unknown:pinot.good.knob." not in {
            k.split("+")[0] for k in _keys(rep)}
        assert not any(k.startswith("unknown:") for k in _keys(rep))


# ---------------------------------------------------------------------------
# kernel-purity checker
# ---------------------------------------------------------------------------

class TestPurityChecker:
    def test_impure_calls_inside_factory_flagged(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/ops/kernels.py": '''
            import time
            import jax

            def make_kernel(plan):
                def kern(cols):
                    t = time.time()
                    return cols[0] * t
                return kern

            def compile_it(plan):
                return jax.jit(make_kernel(plan))
        '''}, "purity")
        assert _keys(rep) == {"kern:time.time"}

    def test_host_sync_and_module_mutation_flagged(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/ops/kernels.py": '''
            import jax
            import numpy as np

            _cache = {}

            def make_kernel(plan):
                def kern(cols):
                    _cache.update({"k": 1})
                    return np.asarray(cols[0])
                return kern

            def compile_it(plan):
                return jax.jit(make_kernel(plan))
        '''}, "purity")
        assert _keys(rep) == {"kern:np.asarray", "kern:_cache.update"}

    def test_traced_closure_over_helpers(self, tmp_path):
        """The traced set must close over module-local helper calls —
        impurity one call away is the same bug."""
        rep = _run(tmp_path, {"pinot_tpu/ops/kernels.py": '''
            import random
            import jax

            def _helper(x):
                return x * random.random()

            def make_kernel(plan):
                def kern(cols):
                    return _helper(cols[0])
                return kern

            def compile_it(plan):
                return jax.jit(make_kernel(plan))
        '''}, "purity")
        assert _keys(rep) == {"_helper:random.random"}

    def test_def_line_suppression_vets_helper_wholesale(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/ops/kernels.py": '''
            import jax

            # lint: impure(trace-time odometer; contributes nothing traced)
            def _odometer():
                global _count
                _count += 1

            def make_kernel(plan):
                def kern(cols):
                    _odometer()
                    return cols[0]
                return kern

            def compile_it(plan):
                return jax.jit(make_kernel(plan))
        '''}, "purity")
        assert not rep.unsuppressed

    def test_stray_sync_outside_dispatch_modules(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/ops/helper.py": '''
            import jax

            def fetch(x):
                return jax.block_until_ready(x)
        '''}, "purity")
        assert _keys(rep) == {"jax.block_until_ready"}

    def test_pure_kernel_clean(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/ops/kernels.py": '''
            import jax
            import jax.numpy as jnp

            def make_kernel(plan):
                def kern(cols):
                    return jnp.sum(cols[0])
                return kern

            def compile_it(plan):
                return jax.jit(make_kernel(plan))
        '''}, "purity")
        assert not rep.unsuppressed


# ---------------------------------------------------------------------------
# exposition checker (the PR-12 lint, framework edition)
# ---------------------------------------------------------------------------

class TestExpositionChecker:
    def test_dup_kind_flagged(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/a.py": '''
            def f(m):
                m.add_meter("whoops")
        ''', "pinot_tpu/b.py": '''
            def g(m):
                m.set_gauge("whoops", 1)
        '''}, "exposition")
        assert _keys(rep) == {"dup-kind:whoops"}

    def test_wrapped_emission_still_linted(self, tmp_path):
        """The name literal on the line AFTER the open paren (the
        dominant 79-col style in this repo) must still be scanned."""
        rep = _run(tmp_path, {"pinot_tpu/a.py": '''
            def f(m):
                m.add_meter(
                    "wrapped_name")

            def g(m):
                m.set_gauge(
                    "wrapped_name", 1)
        '''}, "exposition")
        assert _keys(rep) == {"dup-kind:wrapped_name"}

    def test_single_kind_clean_and_empty_scan_is_a_finding(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/a.py": '''
            def f(m):
                m.add_meter("fine")
                m.set_gauge("also_fine", 1)
        '''}, "exposition")
        assert not rep.unsuppressed
        rep = _run(tmp_path, {"pinot_tpu/a.py": "x = 1\n"}, "exposition")
        assert _keys(rep) == {"scan:empty"}


# ---------------------------------------------------------------------------
# metrics_docs checker (catalog <-> emissions <-> README, both directions)
# ---------------------------------------------------------------------------

_MD_CATALOG = '''
    METRICS = {
        "documented_and_emitted": "a real metric",
        "documented_never_emitted": "a ghost",
    }
'''


class TestMetricsDocsChecker:
    def _files(self, emit_src, readme="documented_and_emitted "
                                      "documented_never_emitted"):
        return {"pinot_tpu/utils/metrics_catalog.py": _MD_CATALOG,
                "pinot_tpu/a.py": emit_src,
                "README.md": readme}

    def test_both_directions(self, tmp_path):
        rep = _run(tmp_path, self._files('''
            def f(m):
                m.add_meter("documented_and_emitted")
                m.set_gauge("emitted_never_documented", 1)
        '''), "metrics_docs")
        assert _keys(rep) == {"uncataloged:emitted_never_documented",
                              "dead:documented_never_emitted"}

    def test_readme_leg(self, tmp_path):
        files = self._files('''
            def f(m):
                m.add_meter("documented_and_emitted")
                m.add_timing("documented_never_emitted", 1.0)
        ''', readme="only mentions documented_and_emitted")
        rep = _run(tmp_path, files, "metrics_docs")
        assert _keys(rep) == {"undocumented:documented_never_emitted"}

    def test_conditional_name_emits_both_branches(self, tmp_path):
        """'a' if won else 'b' counts BOTH literals as emissions —
        the hedge_won/hedge_wasted shape must not read as dead."""
        rep = _run(tmp_path, {
            "pinot_tpu/utils/metrics_catalog.py": '''
                METRICS = {"won": "w", "lost": "l"}
            ''',
            "pinot_tpu/a.py": '''
                def f(m, is_win):
                    m.add_meter("won" if is_win else "lost")
            ''',
            "README.md": "won lost"}, "metrics_docs")
        assert not rep.unsuppressed

    def test_prefix_composing_helper_out_of_scope(self, tmp_path):
        """A module-local _meter that f-string-composes the name marks
        its call-site literals as namespaced suffixes (cache/core.py),
        while a pass-through _meter's literals are real family names."""
        rep = _run(tmp_path, {
            "pinot_tpu/utils/metrics_catalog.py": '''
                METRICS = {"real_family": "r"}
            ''',
            "pinot_tpu/composed.py": '''
                class Cache:
                    def _meter(self, name):
                        self._m.add_meter(f"{self._prefix}_{name}")

                    def hit(self):
                        self._meter("hits")
            ''',
            "pinot_tpu/passthrough.py": '''
                class Residency:
                    def _meter(self, name, value=1):
                        self._m.add_meter(name, value)

                    def touch(self):
                        self._meter("real_family")
                        self._meter("sneaky_unlisted")
            ''',
            "README.md": "real_family"}, "metrics_docs")
        assert _keys(rep) == {"uncataloged:sneaky_unlisted"}

    def test_missing_catalog_is_a_finding_in_real_package(self, tmp_path):
        rep = _run(tmp_path, {
            "pinot_tpu/utils/metrics.py": "x = 1\n",
            "pinot_tpu/a.py": 'def f(m):\n    m.add_meter("x")\n'},
            "metrics_docs")
        assert _keys(rep) == {"catalog:missing"}
        # fixture trees without the registry module stay silent (a
        # FRESH tree: _index materializes cumulatively into tmp_path)
        rep = _run(tmp_path / "bare", {"pinot_tpu/b.py": "y = 1\n"},
                   "metrics_docs")
        assert not rep.unsuppressed


# ---------------------------------------------------------------------------
# framework: parse errors, baseline round-trip, CLI
# ---------------------------------------------------------------------------

class TestFramework:
    def test_syntax_error_fails_gate_not_tool(self, tmp_path):
        rep = _run(tmp_path, {"pinot_tpu/bad.py": "def broken(:\n"},
                   "exposition")
        assert any(f.checker == "parse" for f in rep.unsuppressed)

    def test_baseline_round_trip_and_stale_detection(self, tmp_path):
        files = {"pinot_tpu/mod.py": LOCKED_CLASS.format(suffix="")}
        rep = _run(tmp_path, files, "locks")
        assert rep.unsuppressed

        # bootstrap skeleton -> TODO reasons do NOT count
        bpath = tmp_path / "BASE.json"
        write_baseline(str(bpath), rep.unsuppressed)
        skeleton = json.loads(bpath.read_text())
        assert all(e["reason"].startswith("TODO")
                   for e in skeleton["findings"])

        # a written reason accepts the finding
        skeleton["findings"][0]["reason"] = "gauge read; torn value ok"
        bpath.write_text(json.dumps(skeleton))
        rep2 = _run(tmp_path, files, "locks",
                    baseline=load_baseline(str(bpath)))
        assert not rep2.unsuppressed
        assert len(rep2.baselined) == 1
        assert rep2.baselined[0].reason == "gauge read; torn value ok"

        # an EMPTY reason is ignored (the ledger, not a mute button)
        skeleton["findings"][0]["reason"] = ""
        bpath.write_text(json.dumps(skeleton))
        rep3 = _run(tmp_path, files, "locks",
                    baseline=load_baseline(str(bpath)))
        assert rep3.unsuppressed

        # fixing the bug turns the entry stale (surfaced, not failing)
        skeleton["findings"][0]["reason"] = "valid reason"
        bpath.write_text(json.dumps(skeleton))
        fixed = {"pinot_tpu/mod.py": LOCKED_CLASS.format(suffix="")
                 .replace("return self._hits",
                          "with self._lock:\n"
                          "                return self._hits")}
        rep4 = _run(tmp_path, fixed, "locks",
                    baseline=load_baseline(str(bpath)))
        assert not rep4.unsuppressed
        assert len(rep4.stale_baseline) == 1

    def test_baseline_key_survives_line_drift(self, tmp_path):
        """Keys are built from stable names, not line numbers — an
        unrelated edit above the finding must not churn the baseline."""
        files = {"pinot_tpu/mod.py": LOCKED_CLASS.format(suffix="")}
        rep = _run(tmp_path, files, "locks")
        key = rep.unsuppressed[0].key
        shifted = {"pinot_tpu/mod.py":
                   "# a new comment\n# another\n\n" +
                   textwrap.dedent(LOCKED_CLASS.format(suffix=""))}
        rep2 = _run(tmp_path, shifted, "locks")
        assert rep2.unsuppressed[0].key == key

    def test_cli_exit_codes_and_json(self, tmp_path, capsys):
        for rel, src in FP_FILES.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(src))
        rc = cli_main(["--root", str(tmp_path), "--checker", "failpoints",
                       "--no-baseline", "--json"])
        report = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert report["counts"]["unsuppressed"] == 3

        # write a baseline, justify every entry, gate goes green
        bpath = tmp_path / "B.json"
        rc = cli_main(["--root", str(tmp_path), "--checker", "failpoints",
                       "--no-baseline", "--write-baseline", str(bpath)])
        assert rc == 0
        data = json.loads(bpath.read_text())
        for e in data["findings"]:
            e["reason"] = "accepted for the fixture"
        bpath.write_text(json.dumps(data))
        rc = cli_main(["--root", str(tmp_path), "--checker", "failpoints",
                       "--baseline", str(bpath)])
        assert rc == 0
        capsys.readouterr()

    def test_cli_missing_baseline_is_usage_error(self, tmp_path):
        rc = cli_main(["--root", str(tmp_path),
                       "--baseline", str(tmp_path / "nope.json")])
        assert rc == 2


# ---------------------------------------------------------------------------
# arming the sites the checker found never armed (chaos coverage gaps)
# ---------------------------------------------------------------------------

class TestFailpointArming:
    """Behavioral tests for the five sites the `failpoints` checker
    surfaced as never armed by any test — each exercises the degrade
    contract the site's SITES entry documents."""

    def test_netframe_send_torn_frames_cleanly_content_fails(self):
        """netframe.send torn=: the frame arrives WHOLE (length prefix
        matches the truncated bytes — stream framing never desyncs) but
        its content no longer decodes."""
        from pinot_tpu.utils.netframe import recv_raw_frame, send_raw_frame
        a, b = socket.socketpair()
        try:
            payload = json.dumps({"op": "set", "key": "x" * 64}).encode()
            with failpoints.armed("netframe.send", torn=True, times=1):
                send_raw_frame(a, payload)
            got = fired = recv_raw_frame(b)
            assert fired is not None and len(got) < len(payload)
            with pytest.raises(json.JSONDecodeError):
                json.loads(got)
            # the stream is NOT desynced: the next frame decodes fine
            send_raw_frame(a, payload)
            assert json.loads(recv_raw_frame(b)) == json.loads(payload)
        finally:
            a.close()
            b.close()

    def test_cache_remote_get_error_degrades_to_miss_then_breaker(self):
        """cache.remote.get: a dying remote tier must read as a MISS
        (total-function contract), count errors, and trip the breaker
        after consecutive failures — never raise to the query path."""
        from pinot_tpu.cache.remote import (
            CIRCUIT_CLOSED, CIRCUIT_OPEN, RemoteCacheBackend)
        from pinot_tpu.utils.metrics import MetricsRegistry
        m = MetricsRegistry()
        be = RemoteCacheBackend("127.0.0.1:1", failure_threshold=2,
                                reset_seconds=60.0, metrics=m,
                                labels={"tier": "t"})
        assert be.breaker.state == CIRCUIT_CLOSED
        with failpoints.armed("cache.remote.get",
                              error=FailpointError("remote tier dying")):
            assert be.get("k1") is None
            assert be.get("k2") is None
        assert be.breaker.state == CIRCUIT_OPEN
        assert m.meter("remote_cache_errors", labels={"tier": "t"}) >= 2

    def test_controller_task_assign_error_leaves_task_pending(self):
        """controller.task.assign: a raise in the grant leaves the task
        PENDING — the lease was never handed out, so no worker believes
        it owns work the queue never recorded as leased."""
        from pinot_tpu.controller.task_manager import (
            LEASED, PENDING, TaskConfig, TaskQueue)
        q = TaskQueue()
        e = q.submit(TaskConfig("PurgeTask", "t_OFFLINE", ["s0"]))
        with failpoints.armed("controller.task.assign",
                              error=FailpointError("grant chaos"),
                              times=1):
            with pytest.raises(FailpointError):
                q.lease("worker-1")
        assert q.get(e.task_id).state == PENDING
        got = q.lease("worker-1")
        assert got is not None and got.state == LEASED
        assert got.task_id == e.task_id

    def test_mse_mailbox_recv_torn_payload_surfaces_truncated(self):
        """mse.mailbox.recv: the receive-side payload hook — a torn
        frame surfaces to the fold layer truncated (typed decode error
        there), and the queue still drains on EOS."""
        from pinot_tpu.mse.mailbox import FLAG_EOS, MailboxService
        svc = MailboxService("inst_sa_recv")
        svc.start()
        try:
            svc.send(svc.address, "qsa|1|0|0", b"0123456789", FLAG_EOS)
            with failpoints.armed("mse.mailbox.recv", torn=True, times=1):
                got = list(svc.receive_all("qsa|1|0|0", num_senders=1,
                                           timeout=5.0))
            assert got == [b"01234"]
            assert svc.queue_count() == 0
        finally:
            svc.stop()

    @pytest.mark.chaos
    def test_connection_request_torn_response_retries_clean(
            self, tmp_path_factory):
        """connection.request torn=: a truncated broker<-server response
        payload must surface as that server's failure and re-scatter to
        the replica — the query answers exactly, zero exceptions."""
        from pinot_tpu.cluster.mini import MiniCluster
        from tests.queries.harness import (
            build_segments, synthetic_columns, synthetic_schema,
            synthetic_table_config)
        tmp = tmp_path_factory.mktemp("conn_req_chaos")
        docs = 200
        segs = build_segments(
            tmp, synthetic_schema(), synthetic_table_config(),
            [synthetic_columns(docs, seed=31 + i) for i in range(2)])
        c = MiniCluster(num_servers=2)
        c.start()
        try:
            c.add_table("testTable")
            for i, seg in enumerate(segs):
                c.add_segment("testTable", seg, server_idx=i % 2,
                              replicas=[(i + 1) % 2])
            sql = ("SELECT COUNT(*) FROM testTable "
                   "OPTION(skipCache=true)")
            baseline = c.query(sql)
            assert not baseline.exceptions
            with failpoints.armed("connection.request", torn=True,
                                  times=1) as fp:
                resp = c.query(sql)
            assert fp.fired >= 1
            assert not resp.exceptions
            assert resp.rows == baseline.rows
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# error-code registry checker
# ---------------------------------------------------------------------------

EC_REGISTRY = '''
    SERVER_ERROR = 427
    EXECUTION_TIMEOUT = 250

    CODES = {
        "SERVER_ERROR": "server unreachable",
        "EXECUTION_TIMEOUT": "deadline exhausted",
    }
'''


class TestErrorCodeChecker:
    def _files(self, emitter):
        return {"pinot_tpu/utils/errorcodes.py": EC_REGISTRY,
                "pinot_tpu/utils/accounting.py": "X = 1\n",
                "pinot_tpu/broker/mod.py": emitter}

    def test_literal_dict_emission_flagged(self, tmp_path):
        rep = _run(tmp_path, self._files('''
            from pinot_tpu.utils import errorcodes
            USE = (errorcodes.SERVER_ERROR, errorcodes.EXECUTION_TIMEOUT)
            def fail():
                return {"errorCode": 427, "message": "boom"}
        '''), "errorcodes")
        assert _keys(rep) == {"literal:dict:427"}

    def test_literal_comparison_and_get_default_flagged(self, tmp_path):
        rep = _run(tmp_path, self._files('''
            from pinot_tpu.utils import errorcodes
            USE = (errorcodes.SERVER_ERROR, errorcodes.EXECUTION_TIMEOUT)
            def check(e):
                if e.get("errorCode") == 250:
                    return int(e.get("errorCode", 200))
        '''), "errorcodes")
        assert _keys(rep) == {"literal:cmp:250", "literal:default:200"}

    def test_error_response_helper_and_assign_flagged(self, tmp_path):
        rep = _run(tmp_path, self._files('''
            from pinot_tpu.utils import errorcodes
            USE = (errorcodes.SERVER_ERROR, errorcodes.EXECUTION_TIMEOUT)
            def _error_response(code, msg):
                return (code, msg)
            class Boom(Exception):
                ERROR_CODE = 427
            def fail():
                return _error_response(427, "x")
        '''), "errorcodes")
        assert _keys(rep) == {"literal:call:427",
                              "literal:assign:ERROR_CODE"}

    def test_catalog_reference_clean(self, tmp_path):
        rep = _run(tmp_path, self._files('''
            from pinot_tpu.utils import errorcodes
            def fail():
                return {"errorCode": errorcodes.SERVER_ERROR,
                        "message": "boom"}
            def check(e):
                return e.get("errorCode") == errorcodes.EXECUTION_TIMEOUT
        '''), "errorcodes")
        assert not rep.unsuppressed

    def test_phantom_code_flagged(self, tmp_path):
        rep = _run(tmp_path, {
            "pinot_tpu/utils/errorcodes.py": '''
                SERVER_ERROR = 427
                NEVER_USED = 999

                CODES = {"SERVER_ERROR": "x", "NEVER_USED": "y"}
            ''',
            "pinot_tpu/utils/accounting.py": "X = 1\n",
            "pinot_tpu/broker/mod.py": '''
                from pinot_tpu.utils import errorcodes
                USE = errorcodes.SERVER_ERROR
            '''}, "errorcodes")
        assert _keys(rep) == {"dead:NEVER_USED"}

    def test_undescribed_code_flagged(self, tmp_path):
        rep = _run(tmp_path, {
            "pinot_tpu/utils/errorcodes.py": '''
                SERVER_ERROR = 427

                CODES = {}
            ''',
            "pinot_tpu/utils/accounting.py": "X = 1\n",
            "pinot_tpu/broker/mod.py": '''
                from pinot_tpu.utils import errorcodes
                USE = errorcodes.SERVER_ERROR
            '''}, "errorcodes")
        assert _keys(rep) == {"undescribed:SERVER_ERROR"}

    def test_missing_registry_module_flagged(self, tmp_path):
        rep = _run(tmp_path, {
            "pinot_tpu/utils/accounting.py": "X = 1\n"}, "errorcodes")
        assert _keys(rep) == {"registry:missing"}

    def test_inline_suppression_accepted(self, tmp_path):
        rep = _run(tmp_path, self._files('''
            from pinot_tpu.utils import errorcodes
            USE = (errorcodes.SERVER_ERROR, errorcodes.EXECUTION_TIMEOUT)
            def fail():
                # lint: errorcode(wire-compat shim for a foreign code)
                return {"errorCode": 599, "message": "boom"}
        '''), "errorcodes")
        assert not rep.unsuppressed
        assert len(rep.inline_suppressed) == 1


# ---------------------------------------------------------------------------
# THE TIER-1 GATE
# ---------------------------------------------------------------------------

class TestRepoGate:
    """Zero unsuppressed findings across the real repo. A failure here
    names the violation and the fix paths: correct the code, suppress
    inline with `# lint: <code>(<reason>)` where the site is
    correct-by-argument, or (pre-existing accepted findings only) add an
    ANALYSIS_BASELINE.json entry with a written reason."""

    @pytest.fixture(scope="class")
    def report(self):
        from pinot_tpu.analysis import default_baseline_path
        import os
        baseline = {}
        if os.path.exists(default_baseline_path()):
            baseline = load_baseline(default_baseline_path())
        return run_analysis(baseline=baseline)

    def test_zero_unsuppressed_findings(self, report):
        rendered = "\n".join(f.render() for f in report.unsuppressed)
        assert not report.unsuppressed, (
            f"{len(report.unsuppressed)} unsuppressed static-analysis "
            f"finding(s):\n{rendered}")

    def test_no_stale_baseline_entries(self, report):
        stale = "\n".join(" ".join(k) for k in report.stale_baseline)
        assert not report.stale_baseline, (
            f"baseline entries matching no current finding (fix landed? "
            f"remove them):\n{stale}")

    def test_every_baseline_entry_has_a_real_reason(self):
        from pinot_tpu.analysis import default_baseline_path
        import os
        path = default_baseline_path()
        if not os.path.exists(path):
            pytest.skip("no baseline committed")
        data = json.loads(open(path).read())
        bad = [e for e in data["findings"]
               if not str(e.get("reason", "")).strip()
               or str(e["reason"]).startswith("TODO")]
        assert not bad, f"baseline entries without written reasons: {bad}"

    def test_all_checkers_registered_and_ran(self, report):
        from pinot_tpu.analysis import CHECKERS
        assert set(CHECKERS) == {"locks", "hangs", "failpoints", "knobs",
                                 "purity", "exposition", "metrics_docs",
                                 "errorcodes"}
        ran = {f.checker for f in report.findings}
        # lock/knob findings exist (baselined); the others may be clean,
        # which the per-checker fixture tests above keep honest
        assert "locks" in ran
