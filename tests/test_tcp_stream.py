"""TCP stream connector: the network stream SPI (Kafka-consumer analog).

Ref: pinot-plugins/pinot-stream-ingestion/pinot-kafka-2.0
KafkaPartitionLevelConsumer.java, KafkaStreamMetadataProvider — VERDICT
r4 missing #3 / next-round task 7: the SPI must work OUTSIDE the process.
"""
import time

import numpy as np
import pytest

from pinot_tpu.ingest.stream import LongMsgOffset, StreamConfig
from pinot_tpu.ingest.tcp_stream import (StreamProducer, StreamServer,
                                         TcpStreamConsumerFactory)
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)


@pytest.fixture()
def stream_server():
    server = StreamServer()
    server.start()
    yield server
    server.stop()


def _config(server, topic, flush_rows=100):
    return StreamConfig(stream_type="tcp", topic=topic,
                        flush_threshold_rows=flush_rows,
                        flush_threshold_time_ms=3_600_000,
                        properties={"bootstrap": server.address})


class TestTcpStreamSpi:
    def test_publish_fetch_roundtrip(self, stream_server):
        prod = StreamProducer(stream_server.address)
        prod.create_topic("t1", partitions=2)
        for i in range(10):
            prod.publish("t1", {"i": i}, partition=i % 2)
        factory = TcpStreamConsumerFactory()
        cfg = _config(stream_server, "t1")
        meta = factory.create_metadata_provider(cfg)
        assert meta.partition_ids() == [0, 1]
        consumer = factory.create_partition_consumer(cfg, 0)
        batch = consumer.fetch_messages(LongMsgOffset(0), 1000)
        assert [m.value["i"] for m in batch.messages] == [0, 2, 4, 6, 8]
        assert batch.next_offset == LongMsgOffset(5)
        # incremental fetch from a checkpoint
        prod.publish("t1", {"i": 10}, partition=0)
        batch2 = consumer.fetch_messages(batch.next_offset, 1000)
        assert [m.value["i"] for m in batch2.messages] == [10]
        consumer.close()
        prod.close()

    def test_offset_criteria(self, stream_server):
        prod = StreamProducer(stream_server.address)
        prod.create_topic("t2")
        for i in range(7):
            prod.publish("t2", {"i": i})
        factory = TcpStreamConsumerFactory()
        meta = factory.create_metadata_provider(_config(stream_server, "t2"))
        assert meta.start_offset(0, "smallest") == LongMsgOffset(0)
        assert meta.start_offset(0, "largest") == LongMsgOffset(7)


class TestRealtimeOverTcp:
    def test_consume_seal_and_checkpoint_resume(self, stream_server,
                                                tmp_path):
        from pinot_tpu.ingest.realtime_manager import \
            RealtimeSegmentDataManager
        from pinot_tpu.query.executor import QueryExecutor
        from pinot_tpu.server.data_manager import TableDataManager

        prod = StreamProducer(stream_server.address)
        prod.create_topic("rtt")
        for i in range(250):
            prod.publish("rtt", {"id": i, "v": i})
        schema = Schema("rtt", [
            FieldSpec("id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("v", DataType.INT, FieldType.METRIC)])
        tc = TableConfig(name="rtt", table_type=TableType.REALTIME)
        commits = []
        tdm = TableDataManager("rtt_REALTIME")
        mgr = RealtimeSegmentDataManager(
            tc, schema, _config(stream_server, "rtt"), 0, tdm,
            str(tmp_path / "segs"),
            on_commit=lambda n, off: commits.append((n, off)))
        mgr.start()
        deadline = time.time() + 30
        while time.time() < deadline and len(commits) < 2:
            time.sleep(0.05)
        mgr.stop()
        assert len(commits) >= 2, commits
        # all 250 rows visible across sealed + consuming segments
        sdms = tdm.acquire_segments()
        try:
            ex = QueryExecutor([s.segment for s in sdms], use_tpu=False)
            r = ex.execute("SELECT COUNT(*), SUM(id) FROM rtt")
            assert r.rows[0] == (250, float(sum(range(250))))
        finally:
            TableDataManager.release_all(sdms)

        # checkpoint resume: a NEW manager from the last commit offset
        # consumes only the tail (no replay of committed rows)
        last_offset = commits[-1][1]
        for i in range(250, 300):
            prod.publish("rtt", {"id": i, "v": i})
        tdm2 = TableDataManager("rtt_REALTIME")
        mgr2 = RealtimeSegmentDataManager(
            tc, schema, _config(stream_server, "rtt"), 0, tdm2,
            str(tmp_path / "segs2"), start_offset=last_offset)
        mgr2.start()
        deadline = time.time() + 20
        want = 300 - int(str(last_offset))
        while time.time() < deadline:
            sdms = tdm2.acquire_segments()
            try:
                total = sum(s.segment.num_docs for s in sdms)
            finally:
                TableDataManager.release_all(sdms)
            if total >= want:
                break
            time.sleep(0.05)
        mgr2.stop()
        assert total == want


@pytest.mark.chaos
class TestTcpStreamChaos:
    """ingest.tcp.frame failpoint: the wire edge of the consumer SPI."""

    def test_fetch_failpoint_error_surfaces(self, stream_server):
        from pinot_tpu.utils.failpoints import FailpointError, failpoints
        prod = StreamProducer(stream_server.address)
        prod.create_topic("tchaos")
        for i in range(5):
            prod.publish("tchaos", {"i": i})
        consumer = TcpStreamConsumerFactory().create_partition_consumer(
            _config(stream_server, "tchaos"), 0)
        failpoints.arm("ingest.tcp.frame",
                       error=FailpointError("wire chaos"), times=1)
        try:
            with pytest.raises(FailpointError):
                consumer.fetch_messages(LongMsgOffset(0), 1000)
            # one-shot: the next fetch succeeds (backoff-and-retry works)
            batch = consumer.fetch_messages(LongMsgOffset(0), 1000)
            assert [m.value["i"] for m in batch.messages] == list(range(5))
        finally:
            failpoints.disarm("ingest.tcp.frame")
            consumer.close()
            prod.close()

    def test_where_filter_scopes_to_partition(self, stream_server):
        from pinot_tpu.utils.failpoints import FailpointError, failpoints
        prod = StreamProducer(stream_server.address)
        prod.create_topic("tchaos2", partitions=2)
        for i in range(4):
            prod.publish("tchaos2", {"i": i}, partition=i % 2)
        factory = TcpStreamConsumerFactory()
        cfg = _config(stream_server, "tchaos2")
        c0 = factory.create_partition_consumer(cfg, 0)
        c1 = factory.create_partition_consumer(cfg, 1)
        failpoints.arm("ingest.tcp.frame",
                       error=FailpointError("partition 1 only"),
                       where={"partition": 1})
        try:
            assert len(c0.fetch_messages(LongMsgOffset(0), 1000).messages) == 2
            with pytest.raises(FailpointError):
                c1.fetch_messages(LongMsgOffset(0), 1000)
        finally:
            failpoints.disarm("ingest.tcp.frame")
            c0.close()
            c1.close()
            prod.close()
