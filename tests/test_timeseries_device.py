"""Time-series device leg (ISSUE 20): dashboards as device group-bys.

  * parity — the simpleql leaf's `floor((ts - start) / step)` bucket
    FUSES into the device group-by kernel's key
    (pinot.server.timeseries.bucket.enabled) and answers within f32
    tolerance of the host expression-column leaf, across aggregations
    and the full transform set; served leaves meter
    `timeseries_leaf_device`
  * retraces — start/step/count ride staged params (only count_pad is
    in the plan): a sliding dashboard refresh causes ZERO retraces
  * simpleql parens — stage splitting is paren-depth aware: a where()
    predicate like `host = 'a(1)' AND floor(x / 2) > 1` stays ONE stage
    with its argument string verbatim (the old `[^)]*` regex stopped at
    the first close paren and broke both)
  * gapfill — the vectorized stacked-grid transforms
    (timeseries/gapfill.py) match their per-series NaN-aware references
  * leaf cap — the `pinot.timeseries.leaf.max.groups` knob bounds one
    leaf fetch (env-overridable); overflow fails LOUD, never truncates
  * selfmetrics — the PR-14 dogfood dashboards route through the
    device bucket leg (query_history(use_tpu=True)): a third device
    workload beside queries and log search
  * failpoints — `timeseries.leaf.fetch` arms with ctx matching; an
    armed error surfaces instead of silently serving
  * bench smoke — the --timeseries acceptance scenario rides tier-1
"""
import json
import os
import time

import numpy as np
import pytest

from pinot_tpu.health.history import MetricsHistory, MetricsSampler
from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.ops import kernels
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.timeseries import gapfill
from pinot_tpu.timeseries.engine import _parse_simpleql
from pinot_tpu.timeseries.engine import query as ts_query
from pinot_tpu.timeseries.spi import (LeafTimeSeriesPlanNode,
                                      TimeSeriesAggregationNode)
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import SimulatedCrash, failpoints
from pinot_tpu.utils.metrics import MetricsRegistry

HOSTS = ["a(1)", "h1", "h2", "h3"]
T0, STEP, BUCKETS = 1000, 20, 6
T1 = T0 + BUCKETS * STEP


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tssegs")
    schema = Schema("metrics", [
        FieldSpec("ts", DataType.LONG, FieldType.DIMENSION),
        FieldSpec("host", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("value", DataType.DOUBLE, FieldType.METRIC)])
    creator = SegmentCreator(TableConfig(name="metrics"), schema)
    out = []
    for i in range(2):
        rng = np.random.default_rng(900 + i)
        n = 2000
        seg_dir = os.path.join(str(tmp), f"m_{i}")
        creator.build({
            "ts": rng.integers(T0, T1, n),
            "host": np.array([HOSTS[v] for v in
                              rng.integers(0, len(HOSTS), n)], object),
            "value": rng.normal(size=n),
        }, seg_dir, f"m_{i}")
        out.append(load_segment(seg_dir))
    return out


def _engine(name, **overrides):
    return TpuOperatorExecutor(
        config=PinotConfiguration(overrides=overrides),
        metrics_labels={"ts_test": name})


def _meter(eng, name):
    return eng._metrics.meter(
        name, labels={"ts_test": eng._labels["ts_test"]})


def _dash(start=T0, tail="| groupby(host) | sum(host)"):
    return f"fetch(metrics, value, ts, {start}, {T1}, {STEP}) {tail}"


def _series_map(block):
    return {s.tag_key(): s.values for s in block.series}


def _assert_blocks_equal(a, b):
    da, db = _series_map(a), _series_map(b)
    assert set(da) == set(db)
    for key in da:
        # f32 device sums of SIGNED values: cancellation makes relative
        # error meaningless near zero, hence the atol floor
        np.testing.assert_allclose(da[key], db[key], rtol=1e-3,
                                   atol=1e-3, equal_nan=True)


# ---------------------------------------------------------------------------
# device bucket leg parity
# ---------------------------------------------------------------------------
class TestDeviceBucketParity:
    def test_dashboard_parity_and_meter(self, segs):
        eng = _engine("parity")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        bd = ts_query(_dash(), dev)
        bh = ts_query(_dash(), host)
        assert _meter(eng, "timeseries_leaf_device") >= 1
        assert len(bd.series) == len(HOSTS)
        _assert_blocks_equal(bd, bh)

    def test_transform_pipeline_parity(self, segs):
        eng = _engine("transforms")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        for tail in [
            "| sum()",
            "| sum() | rate()",
            "| groupby(host) | avg(host) | gapfill(5)",
            "| groupby(host) | max(host) | interpolate()",
            "| groupby(host) | min(host) | keep_last_value()",
            "| groupby(host) | sum(host) | scale(2.5)",
        ]:
            _assert_blocks_equal(ts_query(_dash(tail=tail), dev),
                                 ts_query(_dash(tail=tail), host))
        assert _meter(eng, "timeseries_leaf_device") >= 6

    def test_knob_disables_the_leg(self, segs):
        eng = _engine("knob", **{
            "pinot.server.timeseries.bucket.enabled": False})
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        _assert_blocks_equal(ts_query(_dash(), dev),
                             ts_query(_dash(), host))
        assert _meter(eng, "timeseries_leaf_device") == 0


class TestZeroRetraceSliding:
    def test_sliding_window_shares_one_kernel(self, segs):
        """The dashboard steady state: start advances every refresh;
        start/step/count ride params, so the warm kernel replays with
        ZERO retraces."""
        eng = _engine("slide")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        ts_query(_dash(T0), dev)   # warm the shape bucket
        t0c = kernels.trace_count()
        for j in range(1, 5):
            start = T0 + j * STEP
            _assert_blocks_equal(ts_query(_dash(start), dev),
                                 ts_query(_dash(start), host))
        assert kernels.trace_count() == t0c
        assert _meter(eng, "timeseries_leaf_device") >= 5


# ---------------------------------------------------------------------------
# simpleql paren-depth splitting (satellite)
# ---------------------------------------------------------------------------
class TestSimpleqlParens:
    def test_where_with_parens_stays_one_stage(self):
        node = _parse_simpleql(
            "fetch(m, value, ts, 0, 100, 10) "
            "| where(host = 'a(1)' AND floor(x / 2) > 1) "
            "| groupby(host) | sum(host)")
        assert isinstance(node, TimeSeriesAggregationNode)
        leaf = node.child
        assert isinstance(leaf, LeafTimeSeriesPlanNode)
        assert leaf.filter_sql == "host = 'a(1)' AND floor(x / 2) > 1"
        assert leaf.group_by_tags == ("host",)

    def test_function_call_commas_stay_one_argument(self):
        node = _parse_simpleql(
            "fetch(m, value, ts, 0, 100, 10) "
            "| where(mod(x, 3) = 1 AND host IN ('a', 'b')) | sum()")
        leaf = node.child
        assert leaf.filter_sql == "mod(x, 3) = 1 AND host IN ('a', 'b')"

    def test_unbalanced_parens_raise(self):
        for bad in [
            "fetch(m, value, ts, 0, 100, 10) | where(floor(x > 1)",
            "fetch(m, value, ts, 0, 100, 10) | sum(",
        ]:
            with pytest.raises(ValueError):
                _parse_simpleql(bad)

    def test_paren_host_value_end_to_end(self, segs):
        """A tag literally containing parens filters correctly through
        the verbatim where() predicate — on both leaf paths."""
        eng = _engine("paren")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        q = _dash(tail="| where(host = 'a(1)') | groupby(host) "
                       "| sum(host)")
        bd, bh = ts_query(q, dev), ts_query(q, host)
        assert len(bd.series) == 1
        assert bd.series[0].tags == {"host": "a(1)"}
        _assert_blocks_equal(bd, bh)


# ---------------------------------------------------------------------------
# vectorized gapfill transforms
# ---------------------------------------------------------------------------
class TestGapfillUnits:
    A = np.array([[np.nan, 1.0, np.nan, np.nan, 4.0, np.nan],
                  [2.0, np.nan, 3.0, np.nan, np.nan, np.nan],
                  [np.nan] * 6])

    def test_keep_last_value(self):
        out = gapfill.keep_last_value(self.A.copy())
        np.testing.assert_allclose(
            out[0], [np.nan, 1, 1, 1, 4, 4], equal_nan=True)
        np.testing.assert_allclose(out[1], [2, 2, 3, 3, 3, 3])
        assert np.isnan(out[2]).all()

    def test_gapfill_constant(self):
        out = gapfill.gapfill(self.A.copy(), 7.5)
        np.testing.assert_allclose(out[0], [7.5, 1, 7.5, 7.5, 4, 7.5])
        np.testing.assert_allclose(out[2], [7.5] * 6)

    def test_interpolate_interior_only(self):
        out = gapfill.interpolate(self.A.copy())
        # interior gaps fill linearly; leading/trailing stay NaN
        np.testing.assert_allclose(
            out[0], [np.nan, 1, 2, 3, 4, np.nan], equal_nan=True)
        np.testing.assert_allclose(
            out[1], [2, 2.5, 3, np.nan, np.nan, np.nan], equal_nan=True)

    def test_rate(self):
        arr = np.array([[0.0, 10.0, 30.0, 30.0]])
        out = gapfill.rate(arr, step=10)
        np.testing.assert_allclose(
            out[0], [np.nan, 1.0, 2.0, 0.0], equal_nan=True)

    def test_aggregate_matches_nan_references(self):
        rng = np.random.default_rng(3)
        stacked = rng.normal(size=(10, 7))
        stacked[rng.random(stacked.shape) < 0.3] = np.nan
        stacked[4] = np.nan   # one all-NaN series
        gids = np.array([0, 0, 1, 1, 1, 2, 2, 0, 2, 1])
        import warnings
        for agg, ref in [("sum", np.nansum), ("avg", np.nanmean),
                         ("min", np.nanmin), ("max", np.nanmax)]:
            out = gapfill.aggregate(stacked, gids, 3, agg)
            for g in range(3):
                rows = stacked[gids == g]
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore", RuntimeWarning)
                    want = ref(rows, axis=0)
                # all-NaN buckets stay NaN (nansum would say 0)
                want = np.where(np.isnan(rows).all(axis=0), np.nan, want)
                np.testing.assert_allclose(out[g], want, equal_nan=True)


# ---------------------------------------------------------------------------
# leaf group cap knob (satellite)
# ---------------------------------------------------------------------------
class TestLeafCapKnob:
    def test_env_override_caps_the_fetch(self, segs, monkeypatch):
        monkeypatch.setenv("PINOT_TPU_TIMESERIES_LEAF_MAX_GROUPS", "1")
        host = QueryExecutor(segs, use_tpu=False)
        # 4 hosts x 6 buckets = 24 group rows > count * 1 = 6: LOUD,
        # never a silent truncation that skews downstream sums
        with pytest.raises(RuntimeError, match="cap"):
            ts_query(_dash(), host)

    def test_default_cap_admits_the_dashboard(self, segs):
        host = QueryExecutor(segs, use_tpu=False)
        block = ts_query(_dash(), host)
        assert len(block.series) == len(HOSTS)


# ---------------------------------------------------------------------------
# selfmetrics dashboards through the device leg
# ---------------------------------------------------------------------------
class TestSelfMetricsDevice:
    def test_dogfood_dashboard_serves_device_side(self, segs):
        from pinot_tpu.health.selfmetrics import query_history
        role = "selfm-dev"
        reg = MetricsRegistry(role)
        hist = MetricsHistory(64)
        sampler = MetricsSampler(role, history=hist, registry=reg)
        base = int(time.time())
        for i in range(10):
            reg.add_meter("queries", 4)
            s = sampler.sample_once()
            s["ts"] = base + i
        eng = _engine("selfm")
        served0 = _meter(eng, "timeseries_leaf_device")
        block = query_history(
            f"fetch(selfmetrics, value, ts, {base}, {base + 10}, 1) "
            f"| where(family = 'queries') | sum() | rate()",
            role=role, history=hist, use_tpu=True, engine=eng)
        assert _meter(eng, "timeseries_leaf_device") > served0
        vals = block.series[0].values
        assert np.allclose(vals[1:], 4.0)


# ---------------------------------------------------------------------------
# failpoint: timeseries.leaf.fetch
# ---------------------------------------------------------------------------
class TestLeafFetchFailpoint:
    def test_armed_site_fires_with_ctx_match(self, segs):
        host = QueryExecutor(segs, use_tpu=False)
        with failpoints.armed("timeseries.leaf.fetch",
                              where={"table": "metrics"}) as fp:
            ts_query(_dash(), host)
            assert fp.fired == 1
        with failpoints.armed("timeseries.leaf.fetch",
                              where={"table": "other"}) as fp:
            ts_query(_dash(), host)
            assert fp.fired == 0

    def test_armed_error_surfaces(self, segs):
        host = QueryExecutor(segs, use_tpu=False)
        with failpoints.armed("timeseries.leaf.fetch",
                              error=SimulatedCrash("leaf kill")):
            with pytest.raises(SimulatedCrash):
                ts_query(_dash(), host)
        assert len(ts_query(_dash(), host).series) == len(HOSTS)


# ---------------------------------------------------------------------------
# bench --timeseries smoke (the acceptance scenario rides tier-1)
# ---------------------------------------------------------------------------
class TestBenchSmoke:
    def test_timeseries_bench_smoke(self, tmp_path):
        import importlib
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench = importlib.import_module("bench")
        out = str(tmp_path / "BENCH_timeseries_smoke.json")
        bench.timeseries_main(smoke=True, out_path=out)
        with open(out) as f:
            data = json.load(f)
        assert data["slide_retraces"] == 0
        assert data["selfmetrics_device"] is True
        assert data["timeseries_leaf_device"] >= 1
