"""Time-series engine SPI + streaming response plane.

Ref: pinot-timeseries (spi/planner + m3ql language plugin),
core/transport/grpc/GrpcQueryServer.java:65 + StreamingReduceService —
VERDICT r4 missing #4/#8.
"""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.timeseries import TimeBuckets, query


@pytest.fixture(scope="module")
def metrics_seg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tsdb")
    schema = Schema("metrics", [
        FieldSpec("ts", DataType.INT, FieldType.DIMENSION),
        FieldSpec("host", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("cpu", DataType.INT, FieldType.METRIC)])
    tc = TableConfig(name="metrics")
    # two hosts, 100 seconds of per-second points; host b misses 40-59
    rows = []
    for t in range(100):
        rows.append((t, "a", t))
        if not 40 <= t < 60:
            rows.append((t, "b", 2 * t))
    cols = {"ts": np.array([r[0] for r in rows]),
            "host": np.array([r[1] for r in rows], object),
            "cpu": np.array([r[2] for r in rows])}
    out = str(tmp / "s0")
    SegmentCreator(tc, schema).build(cols, out, "s0")
    return load_segment(out)


class TestTimeSeries:
    def test_buckets(self):
        b = TimeBuckets(0, 10, 10)
        assert b.end == 100
        assert b.index_of(np.array([0, 9, 10, 99, 100])).tolist() == \
            [0, 0, 1, 9, -1]

    def test_fetch_groupby(self, metrics_seg):
        ex = QueryExecutor([metrics_seg], use_tpu=False)
        block = query("fetch(metrics, cpu, ts, 0, 100, 10) "
                      "| groupby(host)", ex)
        assert block.buckets.count == 10
        by = {s.tags["host"]: s for s in block.series}
        # host a bucket 0 = sum(0..9) = 45
        assert by["a"].values[0] == 45
        # host b bucket 4/5 have no data
        assert np.isnan(by["b"].values[4]) and np.isnan(by["b"].values[5])
        assert by["b"].values[0] == 90

    def test_cross_series_sum_and_transforms(self, metrics_seg):
        ex = QueryExecutor([metrics_seg], use_tpu=False)
        block = query("fetch(metrics, cpu, ts, 0, 100, 10) "
                      "| groupby(host) | sum()", ex)
        assert len(block.series) == 1
        v = block.series[0].values
        assert v[0] == 45 + 90          # both hosts
        assert v[4] == sum(range(40, 50))  # host a only (b gap)
        # keep_last_value fills gaps per series
        block2 = query("fetch(metrics, cpu, ts, 0, 100, 10) "
                       "| groupby(host) | keep_last_value()", ex)
        by = {s.tags["host"]: s for s in block2.series}
        assert by["b"].values[4] == by["b"].values[3]
        # scale
        block3 = query("fetch(metrics, cpu, ts, 0, 100, 10) | sum() "
                       "| scale(0.5)", ex)
        assert block3.series[0].values[0] == (45 + 90) / 2

    def test_where_filter(self, metrics_seg):
        ex = QueryExecutor([metrics_seg], use_tpu=False)
        block = query("fetch(metrics, cpu, ts, 0, 100, 10) "
                      "| where(host = 'a') | sum()", ex)
        assert block.series[0].values[0] == 45

    def test_language_registry(self):
        from pinot_tpu.timeseries import get_language
        assert get_language("simpleql") is not None
        with pytest.raises(KeyError):
            get_language("promql")


class TestStreamingPlane:
    def test_server_streams_blocks_and_broker_reduces(self, tmp_path):
        from pinot_tpu.broker.request_handler import \
            StreamingBrokerRequestHandler
        from pinot_tpu.broker.routing import (BrokerRoutingManager,
                                              RoutingTable, SegmentInfo,
                                              TableRoute)
        from pinot_tpu.server.data_manager import InstanceDataManager
        from pinot_tpu.server.query_server import (QueryServer,
                                                   ServerConnection,
                                                   ServerQueryExecutor)
        schema = Schema("big", [
            FieldSpec("id", DataType.INT, FieldType.DIMENSION)])
        tc = TableConfig(name="big")
        dm = InstanceDataManager("s0")
        creator = SegmentCreator(tc, schema)
        route = TableRoute("big_OFFLINE")
        n_segs = 10
        for i in range(n_segs):
            out = str(tmp_path / f"seg{i}")
            creator.build({"id": np.arange(100) + i * 100}, out, f"big_{i}")
            dm.table("big_OFFLINE").add_segment(load_segment(out))
            route.segments[f"big_{i}"] = SegmentInfo(f"big_{i}", ["s0"])
        server = QueryServer(ServerQueryExecutor(dm, use_tpu=False))
        server.start()
        try:
            conn = ServerConnection(server.host, server.port)
            # raw stream: multiple frames then EOS
            frames = list(conn.request_streaming(
                "big_OFFLINE", "SELECT id FROM big LIMIT 10000", None))
            assert len(frames) >= 3  # ceil(10 segs / 4 per chunk)

            routing = BrokerRoutingManager()
            rt = RoutingTable()
            rt.offline = route
            routing.set_route("big", rt)
            handler = StreamingBrokerRequestHandler(
                routing, {"s0": ServerConnection(server.host, server.port)})
            resp = handler.handle_streaming(
                "SELECT id FROM big ORDER BY id LIMIT 5")
            # order-by falls back to buffered path but still answers
            assert [r[0] for r in resp.result_table.rows] == [0, 1, 2, 3, 4]
            resp2 = handler.handle_streaming("SELECT id FROM big LIMIT 7")
            assert len(resp2.result_table.rows) == 7
            assert not resp2.exceptions
            assert getattr(resp2, "num_streamed_blocks", 0) >= 3
        finally:
            server.stop()
