"""Fleet-wide distributed tracing (ISSUE 12).

Covers: cross-process span propagation + stitching (broker -> servers ->
MSE stages), thread-safe capture-and-attach span handles through the
dispatch ring, tail-based slow-query capture with trace=false, the
/debug/traces + /debug/queries surfaces on every role, trace isolation
under the coalesced dispatch path, same-seed chaos structural identity,
the Timer thread-safety fix, exemplars, and the static exposition lint.
"""
import json
import os
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from pinot_tpu.cluster.mini import MiniCluster
from pinot_tpu.utils import tracing, trace_store
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import failpoints
from pinot_tpu.utils.metrics import MetricsRegistry
from tests.queries.harness import (
    build_segments, synthetic_columns, synthetic_schema,
    synthetic_table_config)

NUM_DOCS = 400


def _spans(tree, name):
    """All spans named `name` anywhere in a trace tree dict."""
    out = []

    def walk(n):
        if n.get("operator") == name:
            out.append(n)
        for c in n.get("children", ()):
            walk(c)

    walk(tree)
    return out


def _shape(tree):
    """Structure-only view of a tree: operator names, child order-free —
    timings/ids/attrs stripped, so two same-seed chaos runs compare
    structurally."""
    return (tree.get("operator"),
            tuple(sorted(_shape(c) for c in tree.get("children", ()))))


# ---------------------------------------------------------------------------
# unit: span handles + trace contexts
# ---------------------------------------------------------------------------

class TestSpanHandles:
    def test_capture_and_attach_across_threads(self):
        rt = tracing.RequestTrace()
        with rt:
            h = tracing.capture()
        assert h is not None

        def worker(i):
            sp = h.child("Worker", idx=i)
            sp.end(done=True)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        d = rt.to_dict()
        assert len(_spans(d, "Worker")) == 16
        assert all(c["done"] for c in _spans(d, "Worker"))

    def test_concurrent_scope_hammer(self):
        """Scopes + handle children mutating one tree from many threads
        never corrupt it (the module tree lock)."""
        rt = tracing.RequestTrace()
        with rt:
            h = tracing.capture()
        errs = []

        def hammer():
            try:
                for i in range(200):
                    sp = h.child("S", i=i)
                    sp.set(j=i)
                    sp.end()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        readers_done = threading.Event()

        def reader():
            while not readers_done.is_set():
                rt.to_dict()

        r = threading.Thread(target=reader)
        r.start()
        for t in threads:
            t.join()
        readers_done.set()
        r.join()
        assert not errs
        assert len(_spans(rt.to_dict(), "S")) == 1600

    def test_graft_and_wire_context(self):
        rt = tracing.RequestTrace(sampled=True)
        wire = rt.wire_context()
        tc = tracing.TraceContext.from_wire(wire)
        assert tc.trace_id == rt.trace_id and tc.sampled
        remote = tracing.RequestTrace(operator="ServerRequest",
                                      trace_id=tc.trace_id)
        with remote:
            with tracing.Scope("Inner", x=1):
                pass
        rt.handle().graft(remote.to_dict())
        d = rt.to_dict()
        assert _spans(d, "ServerRequest")
        assert _spans(d, "Inner")[0]["x"] == 1
        # a torn tree must never fail the query path
        rt.handle().graft({"operator": object()})
        rt.handle().graft(None)

    def test_tracing_off_is_inert(self):
        assert tracing.capture() is None
        assert tracing.current_request() is None
        assert tracing.current_trace_id() is None
        tracing.annotate(x=1)  # no-op, no error
        with tracing.Scope("S") as sc:
            sc.set(y=2)  # inactive scope: no tree, no error


# ---------------------------------------------------------------------------
# satellite: Timer thread-safety + exemplars
# ---------------------------------------------------------------------------

class TestTimerThreadSafety:
    def test_concurrent_update_and_quantile(self):
        """quantile()/samples on a snapshot never race a concurrent
        update (pre-fix: timer() returned the LIVE Timer whose reservoir
        list update() mutates mid-iteration)."""
        reg = MetricsRegistry("t")
        stop = threading.Event()
        errs = []

        def writer():
            i = 0
            while not stop.is_set():
                reg.add_timing("lat", float(i % 100))
                i += 1

        def reader():
            try:
                while not stop.is_set():
                    t = reg.timer("lat")
                    t.quantile(0.95)
                    _ = t.samples
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=writer) for _ in range(4)] + \
                  [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join()
        assert not errs
        # consistent view: a snapshot's counters and reservoir agree
        snap = reg.timer("lat")
        assert snap.count >= len(snap.samples)

    def test_timer_miss_returns_empty_snapshot(self):
        reg = MetricsRegistry("t")
        t = reg.timer("never")
        assert t.count == 0 and t.quantile(0.5) == 0.0

    def test_exemplar_links_metrics_to_traces(self):
        reg = MetricsRegistry("broker")
        reg.add_timing("broker_query_ms", 12.5, exemplar="abc123")
        assert reg.exemplar("broker_query_ms") == "abc123"
        text = reg.prometheus_text()
        assert '# EXEMPLAR pinot_tpu_broker_broker_query_ms ' \
               'trace_id="abc123"' in text
        # exemplar lines are comments: every non-comment line still
        # parses as `name{labels} value`
        for line in text.splitlines():
            if line.startswith("#"):
                continue
            assert re.fullmatch(r'[a-zA-Z_:][\w:]*(\{.*\})? \S+', line), line


# ---------------------------------------------------------------------------
# acceptance: ONE stitched cross-process tree
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def traced_cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("traced")
    data = [synthetic_columns(NUM_DOCS, seed=11 + i) for i in range(4)]
    segs = build_segments(tmp, synthetic_schema(),
                          synthetic_table_config(), data)
    # a tiny dimension table for the MSE join leg
    from pinot_tpu.models.schema import Schema
    from pinot_tpu.models.table_config import TableConfig
    from pinot_tpu.segment.creator import SegmentCreator
    from pinot_tpu.segment.loader import load_segment
    dim_schema = Schema.from_dict({
        "schemaName": "dim",
        "dimensionFieldSpecs": [{"name": "g", "dataType": "STRING"},
                                {"name": "label", "dataType": "STRING"}]})
    creator = SegmentCreator(
        TableConfig.from_dict({"tableName": "dim",
                               "tableType": "OFFLINE"}), dim_schema)
    groups = sorted({str(g) for d in data for g in d["groupCol"]})
    ddir = str(tmp / "dim_0")
    creator.build({"g": np.array(groups),
                   "label": np.array([f"L{g}" for g in groups])},
                  ddir, "dim_0")
    dim_seg = load_segment(ddir)

    c = MiniCluster(num_servers=2, use_tpu=True)
    c.start(with_http=True)
    c.add_table("testTable")
    for i, seg in enumerate(segs):
        c.add_segment("testTable", seg, server_idx=i % 2)
    c.add_table("dim")
    c.add_segment("dim", dim_seg, server_idx=0)
    yield c, data
    c.stop()


class TestStitchedTrace:
    def test_scatter_trace_is_one_stitched_tree(self, traced_cluster):
        """Acceptance: trace=true over a >=2-server scatter returns ONE
        tree containing broker, per-server, and dispatch-phase spans
        with queue wait / batch size / kernel ms / fetch ms / transfer
        bytes attrs."""
        c, _ = traced_cluster
        resp = c.query("SET trace = true; SELECT SUM(intCol) "
                       "FROM testTable WHERE intCol >= 100")
        assert not resp.exceptions, resp.exceptions
        tree = resp.trace
        assert tree is not None and tree["operator"] == "BrokerRequest"
        scatters = _spans(tree, "ServerScatter")
        assert len(scatters) >= 2
        assert {s["server"] for s in scatters} == {"server_0", "server_1"}
        servers = _spans(tree, "ServerRequest")
        assert len(servers) >= 2, "server trees not stitched in"
        assert all("queueWaitMs" in s for s in servers)
        dispatches = _spans(tree, "DeviceDispatch")
        assert dispatches, "device dispatch phase missing"
        for d in dispatches:
            assert "kernelMs" in d and "fetchMs" in d
            assert "batchSize" in d and "queueWaitMs" in d
            assert "transferBytes" in d and "stagingMs" in d
        assert _spans(tree, "BrokerReduce")
        # the broker retains the sampled trace for /debug/traces
        stored = trace_store.get_store("broker").get(tree["traceId"])
        assert stored is not None and stored["trace"]["traceId"] == \
            tree["traceId"]

    def test_cache_tier_attr_lands_in_trace(self, traced_cluster):
        """The tier-2 segment cache annotates the server's span tree
        (cacheHit / SegmentResultCache scope)."""
        c, _ = traced_cluster
        sql = ("SET trace = true; SELECT MAX(intCol) FROM testTable "
               "WHERE intCol < 900")
        c.query(sql)
        resp = c.query(sql)  # second run: tier-2 hit server-side
        hits = _spans(resp.trace, "SegmentResultCache")
        assert hits and any(s.get("cacheHits", 0) > 0 for s in hits)

    def test_mse_join_trace_has_stage_spans(self, traced_cluster):
        """Acceptance: an MSE join returns the same stitched tree with
        per-stage spans (MseQuery -> MseStage trees shipped back over
        the control plane)."""
        c, _ = traced_cluster
        resp = c.query(
            "SET trace = true; "
            "SELECT d.label, COUNT(*) FROM testTable t "
            "JOIN dim d ON t.groupCol = d.g "
            "GROUP BY d.label ORDER BY d.label LIMIT 100")
        assert not resp.exceptions, resp.exceptions
        tree = resp.trace
        assert tree is not None
        mse = _spans(tree, "MseQuery")
        assert len(mse) == 1
        stages = _spans(tree, "MseStage")
        assert len(stages) >= 2, "per-stage worker trees missing"
        assert {s["instance"] for s in stages} >= {"server_0"}
        # op-level scopes inside the stage trees
        assert _spans(tree, "mse:leaf_agg") or _spans(tree, "mse:scan")
        assert _spans(tree, "mse:send")
        # stage ids distinguish the spans
        assert len({(s["stage"], s["instance"], s.get("workerIdx"))
                    for s in stages}) == len(stages)

    def test_trace_false_returns_no_trace(self, traced_cluster):
        c, _ = traced_cluster
        resp = c.query("SELECT COUNT(*) FROM testTable "
                       "OPTION(skipCache=true)")
        assert resp.trace is None


# ---------------------------------------------------------------------------
# tail-based slow-query capture + /debug surfaces
# ---------------------------------------------------------------------------

class TestSlowQueryCapture:
    @pytest.fixture()
    def slow_cluster(self, tmp_path):
        data = [synthetic_columns(NUM_DOCS, seed=3)]
        segs = build_segments(tmp_path, synthetic_schema(),
                              synthetic_table_config(), data)
        cfg = PinotConfiguration(overrides={
            "pinot.broker.slow.query.threshold.ms": 0.001})
        c = MiniCluster(num_servers=1, config=cfg)
        c.start(with_http=True)
        c.add_table("testTable")
        c.add_segment("testTable", segs[0], server_idx=0)
        yield c
        c.stop()

    def test_slow_query_retained_with_trace_false(self, slow_cluster,
                                                  caplog):
        """Acceptance: a query over the slow threshold is retrievable
        from /debug/traces — stitched server spans included — even with
        trace=false, plus a structured slow-query log line."""
        import logging
        trace_store.get_store("broker").clear()
        with caplog.at_level(logging.WARNING, logger="pinot_tpu.slowquery"):
            resp = slow_cluster.query(
                "SELECT SUM(intCol) FROM testTable "
                "OPTION(skipCache=true)")
        assert resp.trace is None  # client asked for nothing back
        recent = trace_store.get_store("broker").recent()
        assert recent and recent[0]["slow"] is True
        tid = recent[0]["traceId"]
        stored = trace_store.get_store("broker").get(tid)
        # the tail-captured tree is STITCHED: server spans are in it
        assert _spans(stored["trace"], "ServerRequest")
        # structured log line with the trace id
        lines = [r.message for r in caplog.records
                 if "SLOW_QUERY" in r.message]
        assert lines
        payload = json.loads(lines[-1].split("SLOW_QUERY ", 1)[1])
        assert payload["traceId"] == tid
        assert payload["durationMs"] >= 0.001
        # ... and over HTTP
        with urllib.request.urlopen(
                f"http://127.0.0.1:{slow_cluster.http.port}"
                f"/debug/traces/{tid}", timeout=10) as f:
            got = json.loads(f.read())
        assert got["traceId"] == tid and got["slow"] is True
        with urllib.request.urlopen(
                f"http://127.0.0.1:{slow_cluster.http.port}/debug/traces",
                timeout=10) as f:
            listing = json.loads(f.read())
        assert any(e["traceId"] == tid for e in listing["traces"])
        # the exemplar on the broker query timer names the latest trace
        from pinot_tpu.utils.metrics import get_registry
        assert get_registry("broker").exemplar("broker_query_ms")

    def test_debug_queries_shows_inflight_phase(self, slow_cluster):
        trace_store.get_inflight("broker")  # ensure registry exists
        with failpoints.armed("server.execute.before", delay=0.6):
            t = threading.Thread(
                target=slow_cluster.query,
                args=("SELECT COUNT(*) FROM testTable "
                      "OPTION(skipCache=true)",))
            t.start()
            deadline = time.time() + 5
            snap = []
            while time.time() < deadline:
                snap = trace_store.get_inflight("broker").snapshot()
                if snap:
                    break
                time.sleep(0.01)
            assert snap, "in-flight query not visible"
            assert snap[0]["phase"] in ("parse", "route", "scatter",
                                        "gather", "reduce")
            assert "COUNT(*)" in snap[0]["sql"]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{slow_cluster.http.port}"
                    "/debug/queries", timeout=10) as f:
                got = json.loads(f.read())
            assert got["queries"] and "elapsedMs" in got["queries"][0]
            t.join(timeout=10)
        assert trace_store.get_inflight("broker").snapshot() == []


# ---------------------------------------------------------------------------
# trace isolation through the coalesced dispatch path
# ---------------------------------------------------------------------------

class TestTraceIsolation:
    def test_concurrent_traces_never_cross(self, traced_cluster):
        """N concurrent trace=true queries whose launches may coalesce
        into shared batched kernels still produce N disjoint trees: each
        tree carries its own trace id, exactly its own scatter/dispatch
        spans, and the right rows for its own literal."""
        c, data = traced_cluster
        v = np.concatenate([np.asarray(d["intCol"]) for d in data])
        bounds = [100, 200, 300, 400, 500, 600, 700, 800]
        results = [None] * len(bounds)

        def run(i):
            resp = c.query(
                f"SET trace = true; SELECT SUM(intCol), COUNT(*) "
                f"FROM testTable WHERE intCol >= {bounds[i]}")
            results[i] = resp

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(bounds))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        trace_ids = set()
        for i, resp in enumerate(results):
            assert not resp.exceptions, resp.exceptions
            # correctness per literal: no cross-query result mixing
            want = float(v[v >= bounds[i]].sum())
            assert float(resp.rows[0][0]) == pytest.approx(want), i
            tree = resp.trace
            assert tree is not None, i
            trace_ids.add(tree["traceId"])
            # every span in MY tree belongs to MY trace: exactly one
            # scatter per server attempt-set, one grafted ServerRequest
            # per scatter, no duplicated/foreign subtrees
            scatters = _spans(tree, "ServerScatter")
            assert len(scatters) == 2, tree
            assert len(_spans(tree, "ServerRequest")) == 2
            for d_sp in _spans(tree, "DeviceDispatch"):
                # a shared batched launch reports into N distinct trees;
                # per-member attrs must be complete in each
                assert "kernelMs" in d_sp and "batchSize" in d_sp
        assert len(trace_ids) == len(bounds), "trace ids collided"


# ---------------------------------------------------------------------------
# same-seed chaos -> structurally identical trees
# ---------------------------------------------------------------------------

class TestChaosTraceIdentity:
    def _run_once(self, tmp_path, tag, chaos):
        data = [synthetic_columns(NUM_DOCS, seed=5)]
        segs = build_segments(tmp_path / tag, synthetic_schema(),
                              synthetic_table_config(), data)
        c = MiniCluster(num_servers=2, chaos=chaos)
        c.start()
        c.add_table("testTable")
        # same segment on BOTH servers: the retry has a surviving replica
        c.add_segment("testTable", segs[0], server_idx=0, replicas=[1])
        try:
            resp = c.query("SET trace = true; SELECT COUNT(*) "
                           "FROM testTable OPTION(skipCache=true)")
            assert resp.trace is not None
            return resp
        finally:
            c.stop()

    @pytest.mark.chaos
    def test_same_seed_retry_trees_identical(self, tmp_path):
        """A seeded one-shot scatter failure forces a retry; two fresh
        same-seed runs produce structurally identical trace trees
        (operator structure + outcome tags), so a chaos trace is a
        reproducible artifact, not a one-off."""
        def schedule():
            # broker.scatter.before raises on the fan-out thread, so the
            # failure takes the broker's retry path (connection.request
            # errors would be absorbed by the channel's own re-dial)
            return [("broker.scatter.before",
                     {"error": ConnectionError("chaos"), "times": 1,
                      "seed": 1234})]

        r1 = self._run_once(tmp_path, "a", schedule())
        r2 = self._run_once(tmp_path, "b", schedule())
        assert not r1.exceptions and not r2.exceptions
        assert _shape(r1.trace) == _shape(r2.trace)
        # the retry is visible: a failed attempt + a retry sibling
        outcomes1 = sorted(s.get("outcome", "") + (
            "retry" if s.get("retry") else "")
            for s in _spans(r1.trace, "ServerScatter"))
        outcomes2 = sorted(s.get("outcome", "") + (
            "retry" if s.get("retry") else "")
            for s in _spans(r2.trace, "ServerScatter"))
        assert outcomes1 == outcomes2
        assert any("failed" in o for o in outcomes1)
        assert any("retry" in o for o in outcomes1)


# ---------------------------------------------------------------------------
# /metrics on every role
# ---------------------------------------------------------------------------

class TestMetricsEveryRole:
    def test_controller_http_metrics_and_debug(self):
        from pinot_tpu.controller.cluster_state import ClusterState
        from pinot_tpu.controller.http_api import ControllerHttpServer
        from pinot_tpu.utils.metrics import get_registry
        get_registry("controller").add_meter("tables_added")
        srv = ControllerHttpServer(ClusterState())
        srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/metrics",
                    timeout=10) as f:
                text = f.read().decode()
            assert "pinot_tpu_controller_tables_added" in text
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/debug/queries",
                    timeout=10) as f:
                got = json.loads(f.read())
            assert got["role"] == "controller"
        finally:
            srv.stop()

    def test_debug_http_server_for_server_and_minion_roles(self):
        """DebugHttpServer: the exposition surface server/minion/cache
        roles mount (ServerRole.start wires it via
        pinot.server.admin.port)."""
        from pinot_tpu.utils.metrics import get_registry
        from pinot_tpu.utils.trace_store import DebugHttpServer
        get_registry("minion").add_meter("minion_tasks_completed", 0)
        srv = DebugHttpServer(["minion"])
        srv.start()
        try:
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/metrics",
                    timeout=10) as f:
                text = f.read().decode()
            assert "pinot_tpu_minion_minion_tasks_completed" in text
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/health",
                    timeout=10) as f:
                assert f.read() == b"OK"
            trace_store.get_store("minion").record(
                "tid-1", {"operator": "MinionTask"}, sql="task:Purge")
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/debug/traces/tid-1",
                    timeout=10) as f:
                got = json.loads(f.read())
            assert got["trace"]["operator"] == "MinionTask"
        finally:
            srv.stop()

    def test_server_role_admin_knob_disabled(self):
        """pinot.server.admin.port < 0 disables the surface."""
        from pinot_tpu.cluster.roles import _start_admin
        cfg = PinotConfiguration(
            overrides={"pinot.server.admin.port": -1})
        assert _start_admin(cfg, "pinot.server.admin.port",
                            ["server"]) is None


# ---------------------------------------------------------------------------
# satellite: static exposition lint — MIGRATED into the analysis framework
# (pinot_tpu/analysis/checkers/exposition.py, gated by
# tests/test_static_analysis.py). Only the live-registry belt-and-braces
# check stays here.
# ---------------------------------------------------------------------------

class TestExpositionLive:
    def test_live_exposition_has_one_type_per_name(self):
        """Belt-and-braces on a real registry page (the static lint
        itself now lives in the analysis framework)."""
        reg = MetricsRegistry("lint")
        reg.add_meter("a", labels={"x": "1"})
        reg.add_meter("a", labels={"x": "2"})
        reg.set_gauge("b", 1.0)
        reg.add_timing("c", 5.0)
        text = reg.prometheus_text()
        names = [ln.split()[2] for ln in text.splitlines()
                 if ln.startswith("# TYPE")]
        assert len(names) == len(set(names))


# ---------------------------------------------------------------------------
# minion task traces
# ---------------------------------------------------------------------------

class TestMinionTaskTrace:
    def test_task_trace_rides_completion(self, tmp_path):
        """A purge task's span tree returns in the TaskEntry result
        (retrievable via /tasks/{id} semantics) with execute/upload/
        commit phases."""
        from tests.test_minion import _mini_cluster  # shared harness
        from pinot_tpu.controller.tasks import TaskConfig
        cluster, names = _mini_cluster(tmp_path, n_segments=1, minions=1,
                                       num_servers=1)
        try:
            entry = cluster.submit_task(TaskConfig(
                "PurgeTask", "ct_OFFLINE", names,
                {"purgePredicate": "ts < 30"}))
            done = cluster.wait_task(entry["task_id"], timeout_s=30)
            assert done["state"] == "COMPLETED", done
            result = done["result"]
            assert result.get("traceId")
            tree = result.get("trace")
            assert tree and tree["operator"] == "MinionTask"
            assert _spans(tree, "TaskExecute")
            assert _spans(tree, "TaskUpload")
        finally:
            cluster.stop()


# ---------------------------------------------------------------------------
# tier-1 smoke of the overhead bench
# ---------------------------------------------------------------------------

class TestTracingBenchSmoke:
    def test_trace_overhead_bench_smoke(self):
        """--trace-overhead at smoke scale: the stitched tree exists and
        tracing-off overhead stays inside the (noise-scaled) smoke
        bounds — wired into tier-1 (writes no artifact in smoke mode).
        One retry: the quantitative leg measures ~20ms scatters on a
        shared 2-core box where a worst-case contention window can
        exceed even the scaled bound; a REAL shadow-path regression
        fails both attempts."""
        import importlib
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench = importlib.import_module("bench")
        try:
            bench.trace_overhead_main(smoke=True)
        except AssertionError:
            bench.trace_overhead_main(smoke=True)
