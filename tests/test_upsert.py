"""Upsert/dedup: primary-key last-wins visibility + duplicate dropping
(ref ConcurrentMapPartitionUpsertMetadataManager, SURVEY.md §2.3)."""
import time

import numpy as np
import pytest

from pinot_tpu.ingest import InMemoryStream, StreamConfig
from pinot_tpu.ingest.realtime_manager import RealtimeSegmentDataManager
from pinot_tpu.models import (DataType, DedupConfig, FieldSpec, FieldType,
                              Schema, TableConfig, TableType, UpsertConfig)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.upsert import (
    PartitionDedupMetadataManager, PartitionUpsertMetadataManager,
    ignore_nulls_merger, increment_merger)
from pinot_tpu.server.data_manager import TableDataManager


def make_schema():
    return Schema("u", [
        FieldSpec("pk", DataType.LONG),
        FieldSpec("ver", DataType.LONG),
        FieldSpec("val", DataType.DOUBLE, FieldType.METRIC),
    ], primary_key_columns=["pk"])


def upsert_config():
    tc = TableConfig("u", TableType.REALTIME)
    tc.upsert = UpsertConfig(mode="FULL", comparison_column="ver")
    return tc


class TestUpsertManager:
    def test_last_wins_across_rows(self, tmp_path):
        topic = InMemoryStream("u_topic", 1)
        try:
            tdm = TableDataManager("u_REALTIME")
            sc = StreamConfig(stream_type="inmemory", topic="u_topic",
                              flush_threshold_rows=10_000)
            mgr = RealtimeSegmentDataManager(
                upsert_config(), make_schema(), sc, 0, tdm, str(tmp_path))
            # pk=1 written 3 times with increasing version; pk=2 once
            topic.publish({"pk": 1, "ver": 1, "val": 10.0})
            topic.publish({"pk": 2, "ver": 1, "val": 100.0})
            topic.publish({"pk": 1, "ver": 2, "val": 20.0})
            topic.publish({"pk": 1, "ver": 3, "val": 30.0})
            mgr.start()
            deadline = time.time() + 10
            while time.time() < deadline and mgr.mutable.num_docs < 4:
                time.sleep(0.05)
            mgr.stop()
            sdms = tdm.acquire_segments()
            ex = QueryExecutor([s.segment for s in sdms], use_tpu=False)
            r = ex.execute("SELECT COUNT(*), SUM(val) FROM u LIMIT 10")
            assert r.rows[0][0] == 2            # one row per pk visible
            assert r.rows[0][1] == pytest.approx(30.0 + 100.0)
            assert mgr.upsert_manager.num_primary_keys == 2
            TableDataManager.release_all(sdms)
        finally:
            InMemoryStream.delete("u_topic")

    def test_out_of_order_version_ignored(self):
        from pinot_tpu.ingest.mutable_segment import MutableSegment
        m = PartitionUpsertMetadataManager(["pk"], "ver")
        seg = MutableSegment("s1", upsert_config(), make_schema())
        seg.index({"pk": 1, "ver": 5, "val": 1.0})
        m.add_row(seg, 0, {"pk": 1, "ver": 5, "val": 1.0})
        seg.index({"pk": 1, "ver": 3, "val": 2.0})  # stale update
        m.add_row(seg, 1, {"pk": 1, "ver": 3, "val": 2.0})
        mask = seg.valid_doc_ids.to_mask()
        assert mask[0] and not mask[1]

    def test_seal_preserves_upsert_visibility(self, tmp_path):
        topic = InMemoryStream("u_seal", 1)
        try:
            tdm = TableDataManager("u_REALTIME")
            sc = StreamConfig(stream_type="inmemory", topic="u_seal",
                              flush_threshold_rows=3)
            mgr = RealtimeSegmentDataManager(
                upsert_config(), make_schema(), sc, 0, tdm, str(tmp_path))
            for i, (pk, ver, val) in enumerate(
                    [(1, 1, 1.0), (2, 1, 2.0), (3, 1, 3.0),   # seg 1 seals
                     (1, 2, 10.0), (4, 1, 4.0)]):             # seg 2 consuming
                topic.publish({"pk": pk, "ver": ver, "val": val})
            mgr.start()
            deadline = time.time() + 10
            while time.time() < deadline:
                sdms = tdm.acquire_segments()
                total = sum(s.segment.num_docs for s in sdms)
                TableDataManager.release_all(sdms)
                if total >= 5:
                    break
                time.sleep(0.05)
            mgr.stop()
            sdms = tdm.acquire_segments()
            ex = QueryExecutor([s.segment for s in sdms], use_tpu=False)
            r = ex.execute("SELECT COUNT(*), SUM(val) FROM u LIMIT 10")
            # pk1's sealed-segment row superseded by consuming-segment row
            assert r.rows[0][0] == 4
            assert r.rows[0][1] == pytest.approx(10.0 + 2.0 + 3.0 + 4.0)
            TableDataManager.release_all(sdms)
        finally:
            InMemoryStream.delete("u_seal")


class TestPartialUpsertMergers:
    def test_ignore_nulls(self):
        out = ignore_nulls_merger({"a": 1, "b": 2}, {"a": 5, "b": None})
        assert out == {"a": 5, "b": 2}

    def test_increment(self):
        m = increment_merger(["cnt"])
        out = m({"cnt": 3, "x": "old"}, {"cnt": 2, "x": "new"})
        assert out == {"cnt": 5, "x": "new"}


class TestDedup:
    def test_duplicates_dropped(self, tmp_path):
        topic = InMemoryStream("d_topic", 1)
        try:
            schema = make_schema()
            tc = TableConfig("u", TableType.REALTIME)
            tc.dedup = DedupConfig()
            tdm = TableDataManager("u_REALTIME")
            sc = StreamConfig(stream_type="inmemory", topic="d_topic",
                              flush_threshold_rows=10_000)
            mgr = RealtimeSegmentDataManager(
                tc, schema, sc, 0, tdm, str(tmp_path))
            for pk in [1, 2, 1, 3, 2, 1]:
                topic.publish({"pk": pk, "ver": 1, "val": 1.0})
            mgr.start()
            deadline = time.time() + 10
            while time.time() < deadline and mgr.mutable.num_docs < 3:
                time.sleep(0.05)
            time.sleep(0.2)  # ensure no extras arrive
            mgr.stop()
            assert mgr.mutable.num_docs == 3  # 1, 2, 3 only
            assert mgr.dedup_manager.num_primary_keys == 3
        finally:
            InMemoryStream.delete("d_topic")
