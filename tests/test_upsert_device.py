"""Device-path upsert: validDocIds as device mask tensors (SURVEY §2.3).

Acceptance (ISSUE 11): an upsert table's query plans through the unified
kernel factory with ZERO host-fallback segments, is bit-identical to the
host result after interleaved upserts, and the steady state shows zero
retraces with mask tensors resident. The mask stages as a
(segment, "__valid__") pseudo-column through the residency tier,
version-stamped by the bitmap mutation counter, so an in-place clear()
invalidates the staged copy — never serves stale validity.
"""
import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig, TableType)
from pinot_tpu.ops import kernels, residency
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query import executor_cpu
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.bitmap import Bitmap
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from tests.queries.harness import assert_responses_equal

SQLS = [
    "SELECT COUNT(*), SUM(m), MIN(m), MAX(m) FROM t WHERE d < 12 LIMIT 10",
    "SELECT COUNT(*) FROM t LIMIT 10",
    "SELECT s, COUNT(*), SUM(m) FROM t GROUP BY s ORDER BY s LIMIT 20",
    "SELECT d, m FROM t WHERE m > 5000 ORDER BY m DESC LIMIT 25",
    "SELECT DISTINCT s FROM t LIMIT 20",
]


@pytest.fixture()
def segs(tmp_path):
    schema = Schema("t", [
        FieldSpec("d", DataType.INT, FieldType.DIMENSION),
        FieldSpec("s", DataType.STRING, FieldType.DIMENSION),
        FieldSpec("m", DataType.INT, FieldType.METRIC),
    ])
    tc = TableConfig("t", TableType.OFFLINE)
    tc.indexing.no_dictionary_columns = ["m"]
    creator = SegmentCreator(tc, schema)
    rng = np.random.default_rng(11)
    out = []
    for i in range(3):
        n = 3000
        cols = {
            "d": rng.integers(0, 20, n).astype(np.int32),
            "s": np.array([f"v{x}" for x in rng.integers(0, 6, n)], object),
            "m": rng.integers(0, 10000, n).astype(np.int32),
        }
        d = str(tmp_path / f"seg_{i}")
        creator.build(cols, d, f"t_{i}")
        out.append(load_segment(d))
    # segments 0 and 1 carry live validDocIds (mixed batch: segment 2
    # stays append-only — its mask row is a constant all-ones)
    for s in out[:2]:
        bm = Bitmap.all_set(s.num_docs)
        for doc in range(0, s.num_docs, 3):
            bm.clear(doc)
        s.valid_doc_ids = bm
    return out


@pytest.fixture()
def host_spy(monkeypatch):
    """Counts host-executor segment runs — the zero-host-fallback probe."""
    calls = []
    orig = executor_cpu.execute_segment

    def spy(seg, ctx):
        calls.append(getattr(seg, "name", "?"))
        return orig(seg, ctx)

    monkeypatch.setattr(executor_cpu, "execute_segment", spy)
    monkeypatch.setattr(
        "pinot_tpu.query.executor.executor_cpu.execute_segment", spy,
        raising=False)
    return calls


class TestDeviceUpsert:
    def test_zero_host_fallback_bit_identical(self, segs, host_spy):
        """The acceptance triple: plans through the kernel factory (zero
        host-fallback segments), bit-identical to the host result after
        interleaved upserts, zero steady-state retraces with masks
        resident."""
        eng = TpuOperatorExecutor()
        cpu = QueryExecutor(segs, use_tpu=False)
        tpu = QueryExecutor(segs, use_tpu=True, engine=eng)
        for sql in SQLS:
            a = cpu.execute(sql)
            host_spy.clear()
            b = tpu.execute(sql)
            assert not a.exceptions and not b.exceptions, \
                (sql, a.exceptions, b.exceptions)
            assert_responses_equal(a, b, sql)
            assert host_spy == [], \
                f"host fallback for {sql!r}: {host_spy}"

        # interleaved upserts: clear more bits (a consuming-segment row
        # superseding sealed rows mutates the bitmap in place)
        v = segs[0].valid_doc_ids
        for doc in [d for d in range(segs[0].num_docs)
                    if v.contains(d)][:200]:
            v.clear(doc)
        for sql in SQLS:
            a = cpu.execute(sql)
            host_spy.clear()
            b = tpu.execute(sql)
            assert_responses_equal(a, b, sql)
            assert host_spy == []

        # steady state: repeat every shape — nothing compiles, nothing
        # ships over the link (masks + columns resident)
        t0 = kernels.trace_count()
        b0 = residency.column_transfer_bytes()
        for sql in SQLS:
            tpu.execute(sql)
        assert kernels.trace_count() - t0 == 0, kernels.trace_log(8)
        assert residency.column_transfer_bytes() - b0 == 0

    def test_mask_mutation_invalidates_staged_copy(self, segs):
        """An in-place clear() between queries must be visible on the
        device path: the version-stamped key makes the stale block
        unreachable. No retrace — only the one mask row re-ships."""
        eng = TpuOperatorExecutor()
        tpu = QueryExecutor(segs, use_tpu=True, engine=eng)
        sql = "SELECT COUNT(*) FROM t LIMIT 10"
        r1 = tpu.execute(sql).rows[0][0]
        v = segs[1].valid_doc_ids
        live = [d for d in range(segs[1].num_docs) if v.contains(d)][:10]
        for d in live:
            v.clear(d)
        t0 = kernels.trace_count()
        r2 = tpu.execute(sql).rows[0][0]
        assert r2 == r1 - len(live)
        assert kernels.trace_count() - t0 == 0

    def test_fully_masked_segment(self, segs):
        """Every doc superseded: the segment contributes nothing, and
        matched counts honor it (num_segments_matched drops)."""
        v = segs[0].valid_doc_ids
        for d in range(segs[0].num_docs):
            if v.contains(d):
                v.clear(d)
        eng = TpuOperatorExecutor()
        cpu = QueryExecutor(segs, use_tpu=False)
        tpu = QueryExecutor(segs, use_tpu=True, engine=eng)
        for sql in SQLS:
            assert_responses_equal(cpu.execute(sql), tpu.execute(sql), sql)

    def test_mse_scan_doc_ids_honor_mask(self, segs):
        """filtered_doc_ids (the MSE leaf-scan join input) rides the topn
        kernel: superseded docs never appear in the returned indices."""
        from pinot_tpu.query.context import QueryContext
        eng = TpuOperatorExecutor()
        ctx = QueryContext.from_sql("SELECT d FROM t WHERE d < 50 LIMIT 10")
        ids = eng.filtered_doc_ids(segs, ctx.filter)
        assert ids[0] is not None and ids[1] is not None
        v0 = segs[0].valid_doc_ids
        assert all(v0.contains(int(d)) for d in ids[0])
        # append-only member of the batch returns the full match set
        assert len(ids[2]) == segs[2].num_docs

    def test_cache_ineligibility_unchanged(self, segs):
        """Upsert segments stay OUT of the tier-2 partial cache (the
        bitmap mutates without a version change) — the ISSUE keeps
        cache/segment_cache.py rules as-is."""
        from pinot_tpu.cache.segment_cache import is_cacheable_segment
        assert not is_cacheable_segment(segs[0])
        assert is_cacheable_segment(segs[2])

    def test_batched_coalesce_with_masks(self, segs):
        """Fingerprint-equal concurrent queries over an upsert batch
        coalesce into one jit(vmap) launch and stay bit-identical to
        per-query execution (the kernel-factory bar, now with masks)."""
        import threading
        from pinot_tpu.utils.config import PinotConfiguration
        cfg = PinotConfiguration(
            overrides={"pinot.server.dispatch.batch.window.ms": 20.0})
        eng = TpuOperatorExecutor(config=cfg)
        ex = QueryExecutor(segs, use_tpu=True, engine=eng)
        cpu = QueryExecutor(segs, use_tpu=False)
        sqls = [f"SELECT COUNT(*), SUM(m) FROM t WHERE d < {k} LIMIT 5"
                for k in (6, 9, 13, 17)]
        for sql in sqls:  # warm shapes
            ex.execute(sql)
        outs = [None] * len(sqls)

        def run(i):
            outs[i] = ex.execute(sqls[i])

        ts = [threading.Thread(target=run, args=(i,))
              for i in range(len(sqls))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for sql, out in zip(sqls, outs):
            assert_responses_equal(cpu.execute(sql), out, sql)


class TestUpsertWarmup:
    def test_seal_warmup_prestages_upsert_columns(self, segs):
        """Warm-before-swap for upsert tables: the result cache rightly
        skips them (mutating bitmap), but the warmup replay still
        prestages their column + mask blocks into HBM residency — the
        zero-gap pipeline's residency half. The first routed query then
        ships zero column bytes."""
        from pinot_tpu.cache.segment_cache import SegmentResultCache
        from pinot_tpu.cache.warmup import FingerprintLog, SegmentWarmup
        from pinot_tpu.query.context import QueryContext
        eng = TpuOperatorExecutor()
        log = FingerprintLog()
        sql = "SELECT COUNT(*), SUM(m) FROM t WHERE d < 12 LIMIT 10"
        ctx = QueryContext.from_sql(sql)
        log.record("t", ctx.fingerprint(), sql)
        warm = SegmentWarmup(log, SegmentResultCache(), use_tpu=True,
                             engine_fn=lambda: eng)
        seg = segs[0]  # upsert segment: live valid_doc_ids
        warm.warm("t", seg)
        assert warm.segments_prestaged == 1
        assert eng.residency.resident_for(seg.name) > 0
        # the first routed query pays compute, not the link
        b0 = residency.column_transfer_bytes()
        ex = QueryExecutor([seg], use_tpu=True, engine=eng)
        r = ex.execute(sql)
        assert not r.exceptions
        assert residency.column_transfer_bytes() - b0 == 0


class TestMeshUpsert:
    def test_doc_sharded_mask_bit_identical(self, segs):
        """The vmask block shards over (segments, docs) like every other
        column block: a 2x2 mesh engine's psum-combined result stays
        bit-identical to the host path with masks live."""
        import jax
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices (conftest sets the device count)")
        from pinot_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(jax.devices()[:4], doc_axis=2)
        eng = TpuOperatorExecutor(mesh=mesh)
        cpu = QueryExecutor(segs, use_tpu=False)
        tpu = QueryExecutor(segs, use_tpu=True, engine=eng)
        for sql in [SQLS[0], SQLS[2]]:
            a, b = cpu.execute(sql), tpu.execute(sql)
            assert not b.exceptions, b.exceptions
            assert_responses_equal(a, b, sql)


class TestStarTreeMaskAware:
    def test_full_bitmap_keeps_star_tree_partial_disables(self, tmp_path):
        """Mask-aware star-tree gating: an all-set bitmap is a no-op mask
        (tree still serves, totals exact); one cleared bit disqualifies
        the pre-aggregated path."""
        schema = Schema("st", [
            FieldSpec("d", DataType.INT, FieldType.DIMENSION),
            FieldSpec("m", DataType.INT, FieldType.METRIC),
        ])
        tc = TableConfig("st", TableType.OFFLINE)
        from pinot_tpu.models.table_config import StarTreeIndexConfig
        tc.indexing.star_tree_configs = [StarTreeIndexConfig(
            dimensions_split_order=["d"],
            function_column_pairs=["SUM__m", "COUNT__*"])]
        creator = SegmentCreator(tc, schema)
        n = 2000
        rng = np.random.default_rng(5)
        cols = {"d": rng.integers(0, 8, n).astype(np.int32),
                "m": rng.integers(0, 100, n).astype(np.int32)}
        d = str(tmp_path / "seg")
        creator.build(cols, d, "st_0")
        seg = load_segment(d)
        sql = "SELECT SUM(m), COUNT(*) FROM st WHERE d < 4 LIMIT 5"
        base = QueryExecutor([seg], use_tpu=False).execute(sql)

        seg.valid_doc_ids = Bitmap.all_set(n)
        full = QueryExecutor([seg], use_tpu=False).execute(sql)
        assert_responses_equal(base, full, sql)

        # clear a matching doc: the mask now bites and the result drops
        dcol = np.asarray(seg.data_source("d").values())
        mcol = np.asarray(seg.data_source("m").values())
        victim = int(np.flatnonzero(dcol < 4)[0])
        seg.valid_doc_ids.clear(victim)
        masked = QueryExecutor([seg], use_tpu=False).execute(sql)
        assert masked.rows[0][0] == base.rows[0][0] - int(mcol[victim])
        assert masked.rows[0][1] == base.rows[0][1] - 1
