"""Vector-similarity device leg (ISSUE 20): ANN as batched matmul.

  * parity — `WHERE vector_similarity(col, qvec, K)` answers through the
    device einsum + lax.top_k kernel BIT-IDENTICALLY to the host
    VectorIndex.top_k walk (exact tables), including hybrid residual
    conjuncts and IVF-pruned tables (probe selection is host-parity by
    construction); served queries meter `vector_served`
  * K-before-filter contract — the K winners are chosen over ALL docs
    and the residual predicate intersects AFTER selection: a filter that
    drops a winner SHRINKS the result, it never promotes the (K+1)-th
    nearest (the host _vector_similarity_mask contract, pinned on both
    paths)
  * fallbacks — disabled knob / OR shapes / ORDER BY / missing index /
    non-cosine metric route to the host path with EXACT structured
    `vector_fallback{reason=}` meters; answers stay correct
  * retraces — the query vector and topK ride staged params, never the
    plan: fingerprint-equal ANN queries with fresh vectors replay ONE
    compiled kernel (ZERO steady-state retraces)
  * serialization — VectorIndex.to_bytes/from_bytes round-trips exactly
    (cells included); torn payloads raise the typed
    VectorIndexCorruption instead of reshaping garbage
  * failpoints — `server.vector.search` arms with ctx matching and a
    seeded decision schedule that replays exactly
  * bench smoke — the --vector acceptance scenario rides tier-1 at
    smoke scale (recall gate, coalesce batching, zero retraces)
"""
import json
import os
import types

import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.ops import kernels, vector_device
from pinot_tpu.ops.engine import TpuOperatorExecutor
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.query.expressions import Function, Identifier, Literal
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.segment.vector_index import (VectorIndex,
                                            VectorIndexCorruption)
from pinot_tpu.utils.config import PinotConfiguration
from pinot_tpu.utils.failpoints import failpoints

DIM = 8
K = 5
N_PER_SEG = 400
N_SEG = 2


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.clear()
    yield
    failpoints.clear()


def _vec_json(row):
    return json.dumps([float(x) for x in row])


def _build_segs(tmp, name, n_per_seg, nseg, seed=7, d=DIM):
    """Clustered embeddings (Gaussian mixture) — the workload the IVF
    coarse layer is built for; exact tables just stay under the
    threshold."""
    centers = np.random.default_rng(100).normal(size=(8, d)) * 2.0
    schema = Schema(name, [
        FieldSpec("id", DataType.INT, FieldType.DIMENSION),
        FieldSpec("vec", DataType.STRING, FieldType.DIMENSION)])
    tc = TableConfig(name=name)
    tc.indexing.vector_index_columns = ["vec"]
    creator = SegmentCreator(tc, schema)
    segs = []
    for i in range(nseg):
        rng = np.random.default_rng(seed + i)
        which = rng.integers(0, len(centers), n_per_seg)
        vecs = (centers[which] + 0.3 * rng.normal(size=(n_per_seg, d))
                ).astype(np.float32)
        out = os.path.join(str(tmp), f"{name}_{i}")
        creator.build({
            "id": np.arange(n_per_seg) + i * n_per_seg,
            "vec": np.array([_vec_json(r) for r in vecs], object),
        }, out, f"{name}_{i}")
        segs.append(load_segment(out))
    return segs


@pytest.fixture(scope="module")
def segs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("vecsegs")
    return _build_segs(tmp, "emb", N_PER_SEG, N_SEG)


def _engine(name, **overrides):
    return TpuOperatorExecutor(
        config=PinotConfiguration(overrides=overrides),
        metrics_labels={"vec_test": name})


def _meter(eng, name, reason=None):
    labels = {"vec_test": eng._labels["vec_test"]}
    if reason is not None:
        labels["reason"] = reason
    return eng._metrics.meter(name, labels=labels)


def _query(rng, segs):
    """Perturb a stored vector — the ANN lookup workload."""
    ix = vector_device._index_of(
        segs[int(rng.integers(0, len(segs)))], "vec")
    base = ix.vectors[int(rng.integers(0, len(ix.vectors)))]
    return (base + 0.05 * rng.normal(size=DIM)).astype(np.float32)


def _sql(qv, kk=K, table="emb", lim=100):
    return (f"SELECT id FROM {table} "
            f"WHERE vector_similarity(vec, '{_vec_json(qv)}', {kk}) "
            f"LIMIT {lim}")


def _ids(resp):
    assert not resp.exceptions, resp.exceptions
    return sorted(int(r[0]) for r in resp.result_table.rows)


# ---------------------------------------------------------------------------
# device/host parity
# ---------------------------------------------------------------------------
class TestDeviceHostParity:
    def test_exact_parity_and_meter(self, segs):
        eng = _engine("parity")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        rng = np.random.default_rng(42)
        for i in range(6):
            qv = _query(rng, segs)
            sql = _sql(qv)
            got = _ids(dev.execute(sql))
            assert got == _ids(host.execute(sql)), sql
            # bit-identical to the index's own answer: per-segment K
            # union (vector_similarity is a per-segment FILTER)
            want = sorted(
                int(ix.top_k(qv, K)[j]) + s * N_PER_SEG
                for s, ix in enumerate(
                    vector_device._index_of(seg, "vec") for seg in segs)
                for j in range(K))
            assert got == want, sql
        assert _meter(eng, "vector_served") == 6

    def test_hybrid_residual_parity(self, segs):
        eng = _engine("hybrid_ok")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        rng = np.random.default_rng(43)
        for cut in (120, 500, 790):
            qv = _query(rng, segs)
            sql = (f"SELECT id FROM emb WHERE id < {cut} AND "
                   f"vector_similarity(vec, '{_vec_json(qv)}', {K}) "
                   f"LIMIT 100")
            assert _ids(dev.execute(sql)) == _ids(host.execute(sql)), sql
        assert _meter(eng, "vector_served") == 3
        assert _meter(eng, "vector_fallback", reason="hybrid") == 0

    def test_k_before_filter_contract(self, segs):
        """Satellite: the residual predicate intersects AFTER the K
        winners are chosen. Dropping the nearest doc via the filter
        SHRINKS the result to K-1 — the (K+1)-th nearest is NEVER
        promoted — on the host path and the device path alike."""
        seg0 = segs[0]
        ix = vector_device._index_of(seg0, "vec")
        qv = ix.vectors[17].astype(np.float32)
        exact = ix.top_k(qv, K + 1)   # K winners + the would-be promotee
        winners, runner_up = exact[:K], int(exact[K])
        drop = int(winners[0])
        sql = (f"SELECT id FROM emb WHERE id != {drop} AND "
               f"vector_similarity(vec, '{_vec_json(qv)}', {K}) "
               f"LIMIT 100")
        host = QueryExecutor([seg0], use_tpu=False)
        eng = _engine("kbefore")
        dev = QueryExecutor([seg0], use_tpu=True, engine=eng)
        want = sorted(int(i) for i in winners if int(i) != drop)
        assert len(want) == K - 1
        assert runner_up not in want
        assert _ids(host.execute(sql)) == want
        assert _ids(dev.execute(sql)) == want
        assert _meter(eng, "vector_served") == 1

    def test_ivf_pruned_parity(self, segs, monkeypatch, tmp_path):
        """With the coarse layer engaged (threshold lowered so the
        build stays test-sized), the device's staged probe-cell mask
        answers exactly like VectorIndex.top_k's nprobe walk — probe
        selection runs through the SAME probe_cells on both paths."""
        monkeypatch.setattr(VectorIndex, "IVF_THRESHOLD", 64)
        ivf_segs = _build_segs(tmp_path, "embivf", 256, 2, seed=50)
        for seg in ivf_segs:
            assert vector_device._index_of(
                seg, "vec").centroids is not None
        eng = _engine("ivf")
        dev = QueryExecutor(ivf_segs, use_tpu=True, engine=eng)
        host = QueryExecutor(ivf_segs, use_tpu=False)
        rng = np.random.default_rng(44)
        for _ in range(5):
            qv = _query(rng, ivf_segs)
            sql = _sql(qv, table="embivf")
            assert _ids(dev.execute(sql)) == _ids(host.execute(sql)), sql
        assert _meter(eng, "vector_served") == 5


# ---------------------------------------------------------------------------
# fallback reasons
# ---------------------------------------------------------------------------
class _StubSeg:
    def __init__(self, index, n=10):
        self._ix = index
        self.num_docs = n

    def data_source(self, col):
        return types.SimpleNamespace(vector_index=self._ix)


class TestFallbacks:
    def test_knob_disables_the_leg(self, segs):
        eng = _engine("knob", **{"pinot.server.vector.enabled": False})
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        qv = _query(np.random.default_rng(45), segs)
        sql = _sql(qv)
        assert _ids(dev.execute(sql)) == _ids(host.execute(sql))
        assert _meter(eng, "vector_served") == 0
        assert _meter(eng, "vector_fallback", reason="disabled") >= 1

    def test_order_by_and_or_shapes_are_hybrid(self, segs):
        eng = _engine("hybrid_fb")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        qv = _query(np.random.default_rng(46), segs)
        lit = _vec_json(qv)
        for sql in [
            f"SELECT id FROM emb "
            f"WHERE vector_similarity(vec, '{lit}', {K}) "
            f"ORDER BY id LIMIT 5",
            f"SELECT id FROM emb "
            f"WHERE vector_similarity(vec, '{lit}', {K}) OR id < 3 "
            f"LIMIT 100",
        ]:
            assert _ids(dev.execute(sql)) == _ids(host.execute(sql)), sql
        assert _meter(eng, "vector_served") == 0
        assert _meter(eng, "vector_fallback", reason="hybrid") == 2

    def test_admit_reasons_exact(self):
        q = np.ones(4, np.float32)
        ok = VectorIndex.build(np.eye(4, dtype=np.float32))
        shape, reason = vector_device.admit([_StubSeg(ok)], "v", q, 2, 64)
        assert shape is not None and reason is None
        cases = [
            ([_StubSeg(None)], q, 2, "noIndex"),
            ([_StubSeg(VectorIndex(np.eye(4, dtype=np.float32),
                                   metric="l2"))], q, 2, "metric"),
            ([_StubSeg(ok)], np.ones(7, np.float32), 2, "precision"),
            ([_StubSeg(ok)], q, 0, "precision"),
            ([_StubSeg(ok)], q, 10_000, "precision"),
        ]
        for stubs, qv, k, want in cases:
            shape, reason = vector_device.admit(stubs, "v", qv, k, 64)
            assert shape is None and reason == want, (reason, want)
            assert reason in vector_device.FALLBACK_REASONS

    def test_split_filter_shapes(self):
        vec = Function("vector_similarity",
                       (Identifier("v"), Literal("[1, 0]"), Literal(2)))
        resid = Function("lt", (Identifier("id"), Literal(5)))
        fn, rest, reason = vector_device.split_filter(vec)
        assert fn is vec and rest is None and reason is None
        fn, rest, reason = vector_device.split_filter(
            Function("and", (resid, vec)))
        assert fn is vec and rest is resid
        # OR around the vector fn / two vector conjuncts: host-side
        for bad in (Function("or", (vec, resid)),
                    Function("and", (vec, vec)),
                    Function("not", (vec,))):
            fn, rest, reason = vector_device.split_filter(bad)
            assert fn is None and reason == "hybrid"


# ---------------------------------------------------------------------------
# zero steady-state retraces
# ---------------------------------------------------------------------------
class TestZeroRetrace:
    def test_fresh_query_vectors_share_one_kernel(self, segs):
        """The query vector and topK ride params, never the plan:
        fingerprint-equal ANN queries replay the SAME compiled kernel
        once the shape is warm."""
        eng = _engine("retrace")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        host = QueryExecutor(segs, use_tpu=False)
        rng = np.random.default_rng(47)
        assert not dev.execute(_sql(_query(rng, segs))).exceptions
        t0 = kernels.trace_count()
        for _ in range(5):
            sql = _sql(_query(rng, segs))
            assert _ids(dev.execute(sql)) == _ids(host.execute(sql))
        assert kernels.trace_count() == t0
        assert _meter(eng, "vector_served") == 6


# ---------------------------------------------------------------------------
# serialization (satellite: typed corruption on torn payloads)
# ---------------------------------------------------------------------------
class TestVectorIndexSerialization:
    def _index(self, n=96, d=6, n_cells=0):
        rng = np.random.default_rng(48)
        return VectorIndex.build(rng.normal(size=(n, d)), n_cells=n_cells)

    def test_roundtrip_exact_and_ivf(self):
        for ix in (self._index(), self._index(n_cells=4)):
            back = VectorIndex.from_bytes(ix.to_bytes())
            np.testing.assert_array_equal(back.vectors, ix.vectors)
            if ix.centroids is None:
                assert back.centroids is None
            else:
                np.testing.assert_array_equal(back.centroids,
                                              ix.centroids)
                np.testing.assert_array_equal(back.assignments,
                                              ix.assignments)
            q = np.ones(6, np.float32)
            np.testing.assert_array_equal(back.top_k(q, 5),
                                          ix.top_k(q, 5))

    def test_torn_payloads_raise_typed_corruption(self):
        """Every proper prefix fails LOUD with VectorIndexCorruption —
        a torn download must never reshape into a silently-wrong
        index."""
        for ix in (self._index(), self._index(n_cells=4)):
            buf = ix.to_bytes()
            assert VectorIndex.from_bytes(buf) is not None
            cuts = {0, 1, 4, len(buf) // 2, len(buf) - 4, len(buf) - 1}
            for cut in cuts:
                with pytest.raises(VectorIndexCorruption):
                    VectorIndex.from_bytes(buf[:cut])
        # the typed error is a ValueError (callers that predate the
        # type still catch it) and names the declared-vs-actual sizes
        buf = self._index().to_bytes()
        with pytest.raises(VectorIndexCorruption, match="truncated"):
            VectorIndex.from_bytes(buf[:-1])
        assert issubclass(VectorIndexCorruption, ValueError)


# ---------------------------------------------------------------------------
# failpoint: server.vector.search
# ---------------------------------------------------------------------------
class TestVectorSearchFailpoint:
    def test_armed_site_fires_with_ctx_match(self, segs):
        eng = _engine("fp")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        qv = _query(np.random.default_rng(49), segs)
        with failpoints.armed("server.vector.search",
                              where={"table": "emb"}) as fp:
            assert not dev.execute(_sql(qv)).exceptions
            assert fp.fired == 1
        # a non-matching ctx never fires
        with failpoints.armed("server.vector.search",
                              where={"table": "other"}) as fp:
            assert not dev.execute(_sql(qv)).exceptions
            assert fp.fired == 0

    def test_seeded_decisions_replay_exactly(self, segs):
        """Decision N is a pure function of (seed, N): re-arming the
        same probability/seed schedule over the same query sequence
        replays the identical fire pattern."""
        eng = _engine("fp_seed")
        dev = QueryExecutor(segs, use_tpu=True, engine=eng)
        rng = np.random.default_rng(51)
        queries = [_sql(_query(rng, segs)) for _ in range(8)]

        def run():
            with failpoints.armed("server.vector.search",
                                  probability=0.5, seed=11) as fp:
                for sql in queries:
                    assert not dev.execute(sql).exceptions
                return list(fp.decisions)

        first, second = run(), run()
        assert first == second
        assert any(fired for fired, _ in first)
        assert not all(fired for fired, _ in first)


# ---------------------------------------------------------------------------
# bench --vector smoke (the acceptance scenario rides tier-1)
# ---------------------------------------------------------------------------
class TestBenchSmoke:
    def test_vector_bench_smoke(self, tmp_path):
        import importlib
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        bench = importlib.import_module("bench")
        out = str(tmp_path / "BENCH_vector_smoke.json")
        bench.vector_main(smoke=True, out_path=out)
        with open(out) as f:
            data = json.load(f)
        assert data["recall_at_k"] >= 0.9
        assert data["coalesce"]["retraces_steady"] == 0
        assert data["coalesce"]["batch_size_max"] >= 2
        assert data["vector_served"] >= 1
