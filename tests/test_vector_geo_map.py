"""Vector, geospatial, and map index families.

Ref: pinot-segment-local creator/impl/vector/HnswVectorIndexCreator.java +
readers/vector/, readers/geospatial/ (H3), segment/index/map/ — VERDICT
r4 missing #6: the last absent index families.
"""
import json

import numpy as np
import pytest

from pinot_tpu.models import (DataType, FieldSpec, FieldType, Schema,
                              TableConfig)
from pinot_tpu.query.executor import QueryExecutor
from pinot_tpu.segment.creator import SegmentCreator
from pinot_tpu.segment.geo_index import GeoIndex, haversine_m
from pinot_tpu.segment.loader import load_segment
from pinot_tpu.segment.map_index import MapIndex
from pinot_tpu.segment.vector_index import VectorIndex


class TestVectorIndex:
    def test_exact_topk_cosine(self):
        rng = np.random.default_rng(0)
        v = rng.normal(size=(500, 16)).astype(np.float32)
        ix = VectorIndex.build(v)
        q = v[123] + rng.normal(scale=0.01, size=16).astype(np.float32)
        top = ix.top_k(q, 5)
        assert top[0] == 123
        # parity with a naive cosine ranking
        vn = v / np.linalg.norm(v, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q)
        naive = np.argsort(vn @ qn)[::-1][:5]
        assert set(top) == set(naive)

    def test_ivf_recall(self):
        rng = np.random.default_rng(1)
        v = rng.normal(size=(8000, 8)).astype(np.float32)
        ix = VectorIndex.build(v)
        assert ix.centroids is not None  # coarse layer engaged
        hits = 0
        for i in range(20):
            q = v[i * 37]
            if i * 37 in ix.top_k(q, 10, nprobe=8):
                hits += 1
        assert hits >= 18  # high self-recall

    def test_serde_roundtrip(self):
        rng = np.random.default_rng(2)
        v = rng.normal(size=(100, 4)).astype(np.float32)
        ix = VectorIndex.build(v)
        ix2 = VectorIndex.from_bytes(ix.to_bytes())
        q = rng.normal(size=4).astype(np.float32)
        assert ix.top_k(q, 7).tolist() == ix2.top_k(q, 7).tolist()

    def test_sql_vector_similarity(self, tmp_path):
        rng = np.random.default_rng(3)
        n, d = 1000, 8
        vecs = rng.normal(size=(n, d)).astype(np.float32)
        schema = Schema("emb", [
            FieldSpec("id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("vec", DataType.STRING, FieldType.DIMENSION)])
        tc = TableConfig(name="emb")
        tc.indexing.vector_index_columns = ["vec"]
        cols = {"id": np.arange(n),
                "vec": np.array([json.dumps([round(float(x), 5)
                                             for x in row])
                                 for row in vecs], object)}
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build(cols, out, "s0")
        seg = load_segment(out)
        ex = QueryExecutor([seg], use_tpu=False)
        q = json.dumps([round(float(x), 5) for x in vecs[42]])
        r = ex.execute(
            f"SELECT id FROM emb WHERE vector_similarity(vec, '{q}', 3)")
        ids = {row[0] for row in r.rows}
        assert 42 in ids and len(ids) == 3


class TestGeoIndex:
    # a few points around Paris (lat, lng)
    POINTS = [(48.8566, 2.3522),    # Paris center
              (48.8606, 2.3376),    # Louvre (~1.2 km)
              (48.8049, 2.1204),    # Versailles (~18 km)
              (45.7640, 4.8357),    # Lyon (~390 km)
              (51.5074, -0.1278)]   # London (~344 km)

    def test_within_distance(self):
        lats = [p[0] for p in self.POINTS]
        lngs = [p[1] for p in self.POINTS]
        ix = GeoIndex.build(lats, lngs)
        near = ix.within_distance(48.8566, 2.3522, 5_000)
        assert near.tolist() == [0, 1]
        wide = ix.within_distance(48.8566, 2.3522, 25_000)
        assert wide.tolist() == [0, 1, 2]

    def test_matches_exact_haversine(self):
        rng = np.random.default_rng(4)
        lats = rng.uniform(48.0, 49.5, 5000)
        lngs = rng.uniform(1.5, 3.5, 5000)
        ix = GeoIndex.build(lats, lngs)
        got = ix.within_distance(48.8566, 2.3522, 20_000)
        d = haversine_m(lats, lngs, 48.8566, 2.3522)
        want = np.flatnonzero(d <= 20_000)
        assert got.tolist() == want.tolist()

    def test_serde_and_sql(self, tmp_path):
        schema = Schema("poi", [
            FieldSpec("name", DataType.STRING, FieldType.DIMENSION),
            FieldSpec("loc", DataType.STRING, FieldType.DIMENSION)])
        tc = TableConfig(name="poi")
        tc.indexing.geo_index_columns = ["loc"]
        names = ["center", "louvre", "versailles", "lyon", "london"]
        cols = {"name": np.array(names, object),
                "loc": np.array([f"{a},{b}" for a, b in self.POINTS],
                                object)}
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build(cols, out, "s0")
        seg = load_segment(out)
        assert seg.data_source("loc").geo_index is not None
        ex = QueryExecutor([seg], use_tpu=False)
        r = ex.execute("SELECT name FROM poi WHERE "
                       "st_within_distance(loc, 48.8566, 2.3522, 5000)")
        assert {row[0] for row in r.rows} == {"center", "louvre"}
        # st_distance transform agrees
        r2 = ex.execute("SELECT name, st_distance(loc, '48.8566,2.3522') "
                        "FROM poi ORDER BY name LIMIT 10")
        dist = {row[0]: row[1] for row in r2.rows}
        assert dist["center"] < 10
        assert 300_000 < dist["london"] < 400_000


class TestMapIndex:
    DOCS = [{"os": "linux", "ram": 64},
            {"os": "mac", "ram": 16},
            {"os": "linux"},
            {}]

    def test_build_and_lookup(self):
        vals = [json.dumps(d) for d in self.DOCS]
        ix = MapIndex.build(vals, len(vals))
        assert ix.keys() == ["os", "ram"]
        assert ix.docs_with_key("ram").tolist() == [0, 1]
        assert ix.docs_with_value("os", "linux").tolist() == [0, 2]
        ix2 = MapIndex.from_bytes(ix.to_bytes())
        assert ix2.value_column("ram").tolist() == [64, 16, None, None]

    def test_sql_map_value(self, tmp_path):
        schema = Schema("hosts", [
            FieldSpec("id", DataType.INT, FieldType.DIMENSION),
            FieldSpec("attrs", DataType.STRING, FieldType.DIMENSION)])
        tc = TableConfig(name="hosts")
        tc.indexing.map_index_columns = ["attrs"]
        cols = {"id": np.arange(4),
                "attrs": np.array([json.dumps(d) for d in self.DOCS],
                                  object)}
        out = str(tmp_path / "s0")
        SegmentCreator(tc, schema).build(cols, out, "s0")
        seg = load_segment(out)
        assert seg.data_source("attrs").map_index is not None
        ex = QueryExecutor([seg], use_tpu=False)
        r = ex.execute("SELECT id, map_value(attrs, 'os') FROM hosts "
                       "ORDER BY id LIMIT 10")
        assert [row[1] for row in r.rows] == ["linux", "mac", "linux", None]
        r2 = ex.execute("SELECT id FROM hosts "
                        "WHERE map_value(attrs, 'os') = 'linux'")
        assert sorted(row[0] for row in r2.rows) == [0, 2]


class TestReviewEdges:
    def test_topk_zero_and_empty(self):
        ix = VectorIndex.build(np.random.default_rng(0)
                               .normal(size=(5, 4)).astype(np.float32))
        assert ix.top_k(np.ones(4, np.float32), 0).tolist() == []
        empty = VectorIndex.build(np.empty((0, 4), np.float32))
        assert empty.top_k(np.ones(4, np.float32), 3).tolist() == []

    def test_antimeridian_wraparound(self):
        lats = [0.0, 0.0]
        lngs = [179.995, -179.995]  # ~1.1 km apart across the date line
        ix = GeoIndex.build(lats, lngs)
        got = ix.within_distance(0.0, 179.995, 5_000)
        assert got.tolist() == [0, 1]

    def test_malformed_points_never_match(self, tmp_path):
        schema = Schema("g", [
            FieldSpec("loc", DataType.STRING, FieldType.DIMENSION)])
        tc = TableConfig(name="g")
        cols = {"loc": np.array(["0.05,0.05", "bad", ""], object)}
        # WITHOUT an index: scan fallback must not crash, bad rows excluded
        out = str(tmp_path / "noidx")
        SegmentCreator(tc, schema).build(cols, out, "noidx")
        seg = load_segment(out)
        ex = QueryExecutor([seg], use_tpu=False)
        r = ex.execute("SELECT COUNT(*) FROM g WHERE "
                       "st_within_distance(loc, 0.0, 0.0, 50000)")
        assert r.rows[0][0] == 1
        # WITH an index: same answer (bad rows index into no cell)
        tc2 = TableConfig(name="g")
        tc2.indexing.geo_index_columns = ["loc"]
        out2 = str(tmp_path / "idx")
        SegmentCreator(tc2, schema).build(cols, out2, "idx")
        seg2 = load_segment(out2)
        ex2 = QueryExecutor([seg2], use_tpu=False)
        r2 = ex2.execute("SELECT COUNT(*) FROM g WHERE "
                         "st_within_distance(loc, 0.0, 0.0, 50000)")
        assert r2.rows[0][0] == 1
